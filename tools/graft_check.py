#!/usr/bin/env python
"""graft-check: the repo's static-analysis gate (ISSUE 7).

Three passes over the real package, one exit code:

  python tools/graft_check.py lint            # pass 1: AST trace-discipline
  python tools/graft_check.py audit           # pass 2: AOT compile-contract
  python tools/graft_check.py costs           # pass 3: compiled-cost diff
  python tools/graft_check.py all --json out.json

- `lint` runs the pure-AST JAX linter (analysis/lint.py, rules
  GR001-GR007) over the package + tools + entry scripts and diffs the
  findings against the checked-in baseline
  (megatron_llm_tpu/analysis/lint_baseline.json). NEW findings fail;
  STALE baseline keys (the code they excused is gone) also fail, so
  the baseline can only shrink honestly. `--list-keys` prints the keys
  of new findings for baseline authoring — every entry needs a
  justification, the loader rejects empty ones.
- `audit` provisions 8 virtual CPU devices, AOT-lowers every
  registered compile contract's reference target (engine entry points,
  train.step on tp2 + dp2x2 meshes, generate_tokens, chunk_topk,
  flash_attention) and checks variant budgets, collective inventories,
  host callbacks, fp64 and temp-memory budgets against the compiled
  artifacts (analysis/audit.py). Pre-existing slow-suite failures are
  triaged in KNOWN_FAILURES.md, which the report links.
- `costs` (ISSUE 15) diffs the audit's per-contract compiled
  cost_analysis FLOPs and memory_analysis temp bytes against the
  checked-in baseline (megatron_llm_tpu/analysis/cost_baseline.json)
  — the compile-cost regression gate: a silent 2x FLOPs regression in
  any jitted entry point fails CI loudly, long before a bench run
  notices the slowdown. Same stale-key/justification workflow as the
  lint baseline: MISSING keys (new audited rows) and STALE keys
  (audited rows gone) both fail; `--update-costs --justify "..."`
  rewrites the baseline with the current measurements, stamping the
  justification on every entry whose value moved. Under `all` the
  costs pass reuses the audit report already computed — one lowering
  pass feeds both gates.

- `verdict` (ROADMAP 5c) runs all three gates and folds them — plus
  the bench headline diff when artifact JSONs are supplied via
  `--bench-artifact` (this run) and `--bench-baseline` (the pinned
  prior run) — into ONE machine-readable go/no-go object: every gate
  named, every failure a reason string, `"verdict": "GO" | "NO-GO"`.
  The per-PR regression gate: what BENCH_r05-era discipline did by
  hand, as machinery. A bench artifact without a baseline is recorded
  informationally (headline echoed, gate not armed); a headline
  tok/s drop past BENCH_HEADLINE_MAX_DROP vs the baseline is NO-GO.

Runs anywhere in < 90 s with JAX_PLATFORMS=cpu (the audit sets it
itself). Exit codes: 0 clean, 1 findings/violations, 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

BASELINE = os.path.join(
    _REPO, "megatron_llm_tpu", "analysis", "lint_baseline.json")
COST_BASELINE = os.path.join(
    _REPO, "megatron_llm_tpu", "analysis", "cost_baseline.json")

# regression tolerances: flops from XLA's HLO cost analysis are
# deterministic per build, so the flops bar is tight (and far below
# the "silent 2x" the gate exists to catch); temp bytes move with
# compiler fusion choices, so the bar is looser.
COST_FLOPS_MAX_RATIO = 1.25
COST_TEMP_MAX_RATIO = 1.5

# verdict's bench-headline gate: the artifact's headline value (tok/s/
# chip) may drop at most this fraction vs the pinned baseline artifact
# before the verdict flips to NO-GO. Wall-clock numbers are noisier
# than compiled costs, so the bar is a ratio, not an equality.
BENCH_HEADLINE_MAX_DROP = 0.05


def run_lint(list_keys: bool = False) -> dict:
    from megatron_llm_tpu.analysis import lint

    findings = lint.lint_paths(lint.default_paths(_REPO), _REPO)
    baseline = lint.load_baseline(BASELINE)
    new, accepted, stale = lint.apply_baseline(findings, baseline)

    for f in new:
        print(f"LINT {f.rule} {f.path}:{f.line}:{f.col} [{f.qualname}] "
              f"{f.message}")
        if list_keys:
            print(f"  key: {f.key}")
    for k in stale:
        print(f"LINT STALE baseline key (code gone — remove the entry): "
              f"{k}")
    ok = not new and not stale
    print(f"lint: {len(findings)} findings, {len(accepted)} baselined, "
          f"{len(new)} new, {len(stale)} stale baseline keys -> "
          f"{'OK' if ok else 'FAIL'}")
    return {
        "ok": ok,
        "total": len(findings),
        "baselined": len(accepted),
        "new": [f.to_dict() for f in new],
        "stale_baseline_keys": stale,
        "baseline": os.path.relpath(BASELINE, _REPO),
    }


def run_audit() -> dict:
    # must precede ANY jax import: the audit meshes need 8 virtual CPU
    # devices and the axon sitecustomize would otherwise grab the TPU
    from megatron_llm_tpu.utils.virtual_mesh import (
        force_virtual_cpu_devices,
    )

    force_virtual_cpu_devices(8)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from megatron_llm_tpu.analysis.audit import audit_repo

    report = audit_repo(_REPO)
    for t in report["targets"]:
        status = "ok" if t["ok"] else "FAIL"
        print(f"AUDIT {t['contract']} [{t['mesh']}] {status} "
              f"collectives={t['facts'].get('collectives')} "
              f"temp_bytes={t['facts'].get('temp_bytes')}")
        for f in t["failures"]:
            print(f"  FAIL: {f}")
    for p in report["marker_problems"]:
        print(f"AUDIT MARKER: {p}")
    n = len(report["targets"])
    print(f"audit: {n} targets over mesh shapes "
          f"{report['mesh_tags']}, {len(report['entry_points_audited'])} "
          f"entry points, markers "
          f"{'consistent' if not report['marker_problems'] else 'BROKEN'} "
          f"-> {'OK' if report['ok'] else 'FAIL'} "
          f"(pre-existing slow-suite triage: {report['known_failures']})")
    return report


def _cost_rows(audit_report: dict) -> dict:
    """One {key: {"flops", "temp_bytes"}} row per (contract, mesh tag)
    from the audit's targets. Instrumented twin rows (quantized /
    telemetry / cost-registry engines) are excluded — the parity
    checks already pin them equal to the plain rows, and one row per
    entry point is what a regression diff needs; device-shortage rows
    (no facts) are skipped."""
    rows = {}
    for t in audit_report.get("targets", []):
        facts = t.get("facts", {})
        if any(facts.get(f) for f in ("quantized", "telemetry", "costs")):
            continue
        if "flops" not in facts:
            continue  # failed to lower / backend without cost analysis
        key = f"{t['contract']}[{t['mesh']}]"
        if key in rows:
            continue  # first (plain) row wins
        tmp = facts.get("temp_bytes")
        rows[key] = {"flops": int(facts["flops"]),
                     "temp_bytes": int(tmp)
                     if isinstance(tmp, int) else None}
    return rows


def load_cost_baseline(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        raw = json.load(fh)
    out = {}
    for e in raw.get("entries", []):
        if not str(e.get("justification", "")).strip():
            raise ValueError(
                f"cost baseline entry {e.get('key')!r} has no "
                f"justification — every pinned cost needs one "
                f"(when/why this number is what it is)")
        out[e["key"]] = e
    return out


def run_costs(audit_report=None, baseline_path: str = COST_BASELINE,
              update: bool = False, justify: str = "") -> dict:
    """Pass 3: diff the audit's per-contract FLOPs/temp-bytes against
    the checked-in baseline (module docstring)."""
    if audit_report is None:
        audit_report = run_audit()
    rows = _cost_rows(audit_report)
    if update:
        if not justify.strip():
            print("costs: --update-costs requires --justify TEXT "
                  "(why the pinned numbers moved)")
            return {"ok": False, "error": "missing --justify"}
        old = {}
        if os.path.exists(baseline_path):
            old = load_cost_baseline(baseline_path)
        entries = []
        for key in sorted(rows):
            prev = old.get(key)
            unchanged = (prev is not None
                         and prev.get("flops") == rows[key]["flops"]
                         and prev.get("temp_bytes")
                         == rows[key]["temp_bytes"])
            entries.append({
                "key": key, **rows[key],
                "justification": prev["justification"] if unchanged
                else justify.strip(),
            })
        with open(baseline_path, "w", encoding="utf-8") as fh:
            json.dump({
                "_comment": [
                    "graft-check compiled-cost baseline (ISSUE 15): the",
                    "audit reference configs' per-contract cost_analysis",
                    "FLOPs and memory_analysis temp bytes, one entry per",
                    "(contract, mesh tag). `graft_check.py costs` fails on",
                    f"flops > {COST_FLOPS_MAX_RATIO}x or temp_bytes >",
                    f"{COST_TEMP_MAX_RATIO}x baseline, on MISSING keys",
                    "(new audited rows) and on STALE keys (rows gone).",
                    "Update: `python tools/graft_check.py costs",
                    "--update-costs --justify '<why the numbers moved>'`.",
                ],
                "entries": entries,
            }, fh, indent=1, sort_keys=False)
            fh.write("\n")
        print(f"costs: baseline updated -> {baseline_path} "
              f"({len(entries)} entries)")
        return {"ok": True, "updated": len(entries),
                "baseline": os.path.relpath(baseline_path, _REPO)}

    try:
        baseline = load_cost_baseline(baseline_path)
    except FileNotFoundError:
        print(f"costs: no baseline at {baseline_path} — create it with "
              f"--update-costs --justify '...'")
        return {"ok": False, "error": "missing baseline",
                "rows": rows}
    regressions, improved, missing = [], [], []
    for key in sorted(rows):
        row = rows[key]
        base = baseline.get(key)
        if base is None:
            missing.append(key)
            continue
        for field, ratio in (("flops", COST_FLOPS_MAX_RATIO),
                             ("temp_bytes", COST_TEMP_MAX_RATIO)):
            now, then = row.get(field), base.get(field)
            if not isinstance(now, int) or not isinstance(then, int) \
                    or then <= 0:
                continue
            if now > then * ratio:
                regressions.append(
                    f"{key}: {field} {then} -> {now} "
                    f"({now / then:.2f}x > the {ratio}x gate) — a "
                    f"compile-cost regression in this entry point; "
                    f"fix it, or re-baseline WITH justification")
            elif now * ratio < then:
                improved.append(
                    f"{key}: {field} {then} -> {now} (improved — "
                    f"refresh the baseline to pin the win)")
    stale = sorted(set(baseline) - set(rows))
    for r in regressions:
        print(f"COSTS REGRESSION {r}")
    for k in missing:
        print(f"COSTS MISSING baseline key {k} (new audited row — add "
              f"it via --update-costs --justify '...')")
    for k in stale:
        print(f"COSTS STALE baseline key {k} (audited row gone — "
              f"refresh the baseline)")
    for n in improved:
        print(f"COSTS NOTE {n}")
    ok = not regressions and not missing and not stale
    print(f"costs: {len(rows)} audited rows vs {len(baseline)} "
          f"baselined, {len(regressions)} regressions, {len(missing)} "
          f"missing, {len(stale)} stale -> {'OK' if ok else 'FAIL'}")
    return {
        "ok": ok,
        "rows": rows,
        "regressions": regressions,
        "missing_keys": missing,
        "stale_keys": stale,
        "improved": improved,
        "flops_max_ratio": COST_FLOPS_MAX_RATIO,
        "temp_max_ratio": COST_TEMP_MAX_RATIO,
        "baseline": os.path.relpath(baseline_path, _REPO),
    }


def _bench_diff(artifact_path, baseline_path):
    """The bench half of the verdict: echo this run's headline, and
    when a pinned baseline artifact rides along, gate the headline
    value (tok/s/chip) against BENCH_HEADLINE_MAX_DROP. Returns None
    when no artifact was supplied (the gate simply isn't armed —
    compile-cost diffs already cover every jitted entry point)."""
    if not artifact_path:
        return None
    with open(artifact_path, "r", encoding="utf-8") as fh:
        art = json.load(fh)
    out = {
        "headline_value": art.get("value"),
        "unit": art.get("unit"),
        "vs_paper_baseline": art.get("vs_baseline"),
        "artifact": artifact_path,
        "max_drop": BENCH_HEADLINE_MAX_DROP,
    }
    # ISSUE 20: the self-driving-fleet acceptance headlines ride the
    # artifact under extra.serving.autonomy — when present, the
    # zero-failed-request bar and the bitwise-resubmit pin become
    # their own gate (absent on legacy artifacts -> unarmed)
    auto = ((art.get("extra") or {}).get("serving") or {}).get(
        "autonomy")
    if auto is not None:
        failed = auto.get("failed_requests")
        bitwise = auto.get("bitwise_resubmits_match")
        out["autonomy"] = {
            "failed_requests": failed,
            "bitwise_resubmits_match": bitwise,
            "recovery_s": auto.get("recovery_s"),
            "convergence_tok_s_ratio": auto.get(
                "convergence_tok_s_ratio"),
            "ok": failed == 0 and bool(bitwise),
        }
    if not baseline_path:
        out |= {"ok": None,
                "note": "no --bench-baseline: headline recorded, "
                        "gate not armed"}
        return out
    with open(baseline_path, "r", encoding="utf-8") as fh:
        base = json.load(fh)
    now, then = art.get("value"), base.get("value")
    if not isinstance(now, (int, float)) \
            or not isinstance(then, (int, float)) or then <= 0:
        out |= {"ok": False,
                "note": f"unreadable headline values "
                        f"(now={now!r}, baseline={then!r})"}
        return out
    ratio = now / then
    out |= {
        "baseline_value": then,
        "baseline_artifact": baseline_path,
        "headline_ratio": round(ratio, 4),
        "ok": ratio >= 1.0 - BENCH_HEADLINE_MAX_DROP,
    }
    return out


def build_verdict(report, bench=None) -> dict:
    """Fold the gate sections (and the optional bench diff) into the
    ONE go/no-go object (ROADMAP 5c): every gate named with its
    boolean, every failure compressed to a reason string a human (or
    the next automation layer) can act on without re-running the
    passes. Pure function over already-computed reports — tested
    directly, no lowering pass needed."""
    gates, reasons = {}, []
    lint = report.get("lint")
    if lint is not None:
        gates["lint"] = bool(lint["ok"])
        if lint["new"]:
            reasons.append(f"lint: {len(lint['new'])} new finding(s) "
                           f"vs baseline")
        if lint.get("stale_baseline_keys"):
            reasons.append(f"lint: {len(lint['stale_baseline_keys'])} "
                           f"stale baseline key(s)")
    audit = report.get("audit")
    if audit is not None:
        gates["audit"] = bool(audit["ok"])
        bad = [t for t in audit.get("targets", []) if not t["ok"]]
        if bad:
            reasons.append(
                "audit: contract failure(s) in "
                + ", ".join(f"{t['contract']}[{t['mesh']}]"
                            for t in bad[:5]))
        if audit.get("marker_problems"):
            reasons.append(f"audit: {len(audit['marker_problems'])} "
                           f"marker problem(s)")
    costs = report.get("costs")
    if costs is not None:
        gates["costs"] = bool(costs["ok"])
        for field in ("regressions", "missing_keys", "stale_keys"):
            if costs.get(field):
                reasons.append(
                    f"costs: {len(costs[field])} {field} "
                    f"(first: {costs[field][0]})"[:200])
    if bench is not None:
        # ok=None (artifact without baseline) is informational, not a
        # gate — only an ARMED bench diff can veto
        if bench.get("ok") is not None:
            gates["bench_headline"] = bool(bench["ok"])
            if not bench["ok"]:
                reasons.append(
                    f"bench: headline {bench.get('headline_value')} vs "
                    f"baseline {bench.get('baseline_value')} "
                    f"(ratio {bench.get('headline_ratio')}, floor "
                    f"{1.0 - BENCH_HEADLINE_MAX_DROP})")
        auto = bench.get("autonomy")
        if auto is not None:
            # ISSUE 20: the chaos-convergence headlines gate on their
            # own — a run that failed requests (or whose resubmits
            # were not bitwise) is a NO-GO regardless of tok/s
            gates["bench_autonomy"] = bool(auto["ok"])
            if not auto["ok"]:
                reasons.append(
                    f"autonomy: {auto.get('failed_requests')} failed "
                    f"request(s), bitwise_resubmits_match="
                    f"{auto.get('bitwise_resubmits_match')} (the "
                    f"zero-failed-request convergence bar)")
    ok = all(gates.values())
    return {
        "verdict": "GO" if ok else "NO-GO",
        "ok": ok,
        "gates": gates,
        "reasons": reasons,
        "bench": bench,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graft_check",
        description="JAX trace-discipline lint + AOT compile-contract "
                    "audit gate")
    ap.add_argument("command",
                    choices=("lint", "audit", "costs", "all", "verdict"))
    ap.add_argument("--json", metavar="PATH",
                    help="write the full machine-readable report here")
    ap.add_argument("--list-keys", action="store_true",
                    help="print baseline keys for new lint findings")
    ap.add_argument("--cost-baseline", metavar="PATH",
                    default=COST_BASELINE,
                    help="compiled-cost baseline to diff against "
                         "(default: analysis/cost_baseline.json)")
    ap.add_argument("--update-costs", action="store_true",
                    help="rewrite the cost baseline with the current "
                         "audit measurements (requires --justify)")
    ap.add_argument("--justify", default="",
                    help="justification stamped on updated cost-"
                         "baseline entries")
    ap.add_argument("--bench-artifact", metavar="PATH", default=None,
                    help="verdict only: this run's bench JSON "
                         "(bench.py output) — headline echoed into "
                         "the verdict")
    ap.add_argument("--bench-baseline", metavar="PATH", default=None,
                    help="verdict only: the pinned prior bench JSON — "
                         "arms the headline-regression gate")
    args = ap.parse_args(argv)

    report = {}
    audit_report = None
    if args.command in ("lint", "all", "verdict"):
        report["lint"] = run_lint(list_keys=args.list_keys)
    if args.command in ("audit", "costs", "all", "verdict"):
        # ONE lowering pass feeds the audit, the cost diff AND verdict
        audit_report = run_audit()
    if args.command in ("audit", "all", "verdict"):
        report["audit"] = audit_report
    if args.command in ("costs", "all", "verdict"):
        report["costs"] = run_costs(
            audit_report, baseline_path=args.cost_baseline,
            update=args.update_costs, justify=args.justify)

    if args.command == "verdict":
        verdict = build_verdict(
            report, bench=_bench_diff(args.bench_artifact,
                                      args.bench_baseline))
        report["verdict"] = verdict
        ok = verdict["ok"]
        for r in verdict["reasons"]:
            print(f"VERDICT REASON: {r}")
        print(f"verdict: gates "
              + " ".join(f"{k}={'OK' if v else 'FAIL'}"
                         for k, v in verdict["gates"].items())
              + f" -> {verdict['verdict']}")
    else:
        ok = all(section["ok"] for section in report.values())
    report["ok"] = ok
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
        print(f"report -> {args.json}")
    print(f"graft-check: {'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
