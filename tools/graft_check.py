#!/usr/bin/env python
"""graft-check: the repo's static-analysis gate (ISSUE 7).

Two passes over the real package, one exit code:

  python tools/graft_check.py lint            # pass 1: AST trace-discipline
  python tools/graft_check.py audit           # pass 2: AOT compile-contract
  python tools/graft_check.py all --json out.json

- `lint` runs the pure-AST JAX linter (analysis/lint.py, rules
  GR001-GR007) over the package + tools + entry scripts and diffs the
  findings against the checked-in baseline
  (megatron_llm_tpu/analysis/lint_baseline.json). NEW findings fail;
  STALE baseline keys (the code they excused is gone) also fail, so
  the baseline can only shrink honestly. `--list-keys` prints the keys
  of new findings for baseline authoring — every entry needs a
  justification, the loader rejects empty ones.
- `audit` provisions 8 virtual CPU devices, AOT-lowers every
  registered compile contract's reference target (engine entry points,
  train.step on tp2 + dp2x2 meshes, generate_tokens, chunk_topk,
  flash_attention) and checks variant budgets, collective inventories,
  host callbacks, fp64 and temp-memory budgets against the compiled
  artifacts (analysis/audit.py). Pre-existing slow-suite failures are
  triaged in KNOWN_FAILURES.md, which the report links.

Runs anywhere in < 60 s with JAX_PLATFORMS=cpu (the audit sets it
itself). Exit codes: 0 clean, 1 findings/violations, 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

BASELINE = os.path.join(
    _REPO, "megatron_llm_tpu", "analysis", "lint_baseline.json")


def run_lint(list_keys: bool = False) -> dict:
    from megatron_llm_tpu.analysis import lint

    findings = lint.lint_paths(lint.default_paths(_REPO), _REPO)
    baseline = lint.load_baseline(BASELINE)
    new, accepted, stale = lint.apply_baseline(findings, baseline)

    for f in new:
        print(f"LINT {f.rule} {f.path}:{f.line}:{f.col} [{f.qualname}] "
              f"{f.message}")
        if list_keys:
            print(f"  key: {f.key}")
    for k in stale:
        print(f"LINT STALE baseline key (code gone — remove the entry): "
              f"{k}")
    ok = not new and not stale
    print(f"lint: {len(findings)} findings, {len(accepted)} baselined, "
          f"{len(new)} new, {len(stale)} stale baseline keys -> "
          f"{'OK' if ok else 'FAIL'}")
    return {
        "ok": ok,
        "total": len(findings),
        "baselined": len(accepted),
        "new": [f.to_dict() for f in new],
        "stale_baseline_keys": stale,
        "baseline": os.path.relpath(BASELINE, _REPO),
    }


def run_audit() -> dict:
    # must precede ANY jax import: the audit meshes need 8 virtual CPU
    # devices and the axon sitecustomize would otherwise grab the TPU
    from megatron_llm_tpu.utils.virtual_mesh import (
        force_virtual_cpu_devices,
    )

    force_virtual_cpu_devices(8)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from megatron_llm_tpu.analysis.audit import audit_repo

    report = audit_repo(_REPO)
    for t in report["targets"]:
        status = "ok" if t["ok"] else "FAIL"
        print(f"AUDIT {t['contract']} [{t['mesh']}] {status} "
              f"collectives={t['facts'].get('collectives')} "
              f"temp_bytes={t['facts'].get('temp_bytes')}")
        for f in t["failures"]:
            print(f"  FAIL: {f}")
    for p in report["marker_problems"]:
        print(f"AUDIT MARKER: {p}")
    n = len(report["targets"])
    print(f"audit: {n} targets over mesh shapes "
          f"{report['mesh_tags']}, {len(report['entry_points_audited'])} "
          f"entry points, markers "
          f"{'consistent' if not report['marker_problems'] else 'BROKEN'} "
          f"-> {'OK' if report['ok'] else 'FAIL'} "
          f"(pre-existing slow-suite triage: {report['known_failures']})")
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graft_check",
        description="JAX trace-discipline lint + AOT compile-contract "
                    "audit gate")
    ap.add_argument("command", choices=("lint", "audit", "all"))
    ap.add_argument("--json", metavar="PATH",
                    help="write the full machine-readable report here")
    ap.add_argument("--list-keys", action="store_true",
                    help="print baseline keys for new lint findings")
    args = ap.parse_args(argv)

    report = {}
    if args.command in ("lint", "all"):
        report["lint"] = run_lint(list_keys=args.list_keys)
    if args.command in ("audit", "all"):
        report["audit"] = run_audit()

    ok = all(section["ok"] for section in report.values())
    report["ok"] = ok
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
        print(f"report -> {args.json}")
    print(f"graft-check: {'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
