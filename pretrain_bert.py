#!/usr/bin/env python
"""Pretrain BERT (ref: /root/reference/pretrain_bert.py).

  python pretrain_bert.py --model_name bert --num_layers 12 ... \\
      --data_path corpus_sentence_document \\
      --tokenizer_type BertWordPieceLowerCase --vocab_file vocab.txt \\
      --train_iters 1000

Masked-LM + sentence-order (binary) loss through the shared Trainer; the
BERT batch fields ride the generic dict data loader.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from megatron_llm_tpu.arguments import args_to_configs, build_base_parser
from megatron_llm_tpu.models import BertModel
from megatron_llm_tpu.parallel import initialize_parallel
from megatron_llm_tpu.tokenizer import build_tokenizer

BERT_KEYS = ["text", "types", "labels", "is_random", "loss_mask",
             "padding_mask"]


def get_batch(raw: dict) -> dict:
    """Loader dict -> BertModel.loss kwargs (ref: pretrain_bert.py:42-68)."""
    labels = np.asarray(raw["labels"])
    return {
        "tokens": jnp.asarray(raw["text"]),
        "labels": jnp.asarray(np.maximum(labels, 0)),  # -1 filler -> 0, masked out
        "loss_mask": jnp.asarray(raw["loss_mask"], jnp.float32),
        "attention_mask": jnp.asarray(raw["padding_mask"]),
        "tokentype_ids": jnp.asarray(raw["types"]),
        "sop_labels": jnp.asarray(raw["is_random"]),
    }


def main(argv=None):
    from megatron_llm_tpu.data.data_samplers import (
        build_pretraining_data_loader,
    )
    from megatron_llm_tpu.data.dataset_utils import (
        build_train_valid_test_datasets,
    )
    from megatron_llm_tpu.training.trainer import Trainer

    p = build_base_parser()
    # --mask_prob is the reference spelling (arguments.py:885)
    p.add_argument("--masked_lm_prob", "--mask_prob", type=float,
                   default=0.15)
    p.add_argument("--short_seq_prob", type=float, default=0.1)
    p.add_argument("--no_binary_head", action="store_true")
    args = p.parse_args(argv)
    if args.train_data_path or args.valid_data_path or args.test_data_path:
        raise SystemExit(
            "--train_data_path/--valid_data_path/--test_data_path are "
            "GPT-family knobs; this entry point uses --data_path + --split"
        )

    from megatron_llm_tpu.parallel.mesh import (
        maybe_initialize_distributed,
    )

    maybe_initialize_distributed()  # before any jax.devices() use
    tokenizer = build_tokenizer(
        args.tokenizer_type or "BertWordPieceLowerCase",
        vocab_file=args.vocab_file,
        make_vocab_size_divisible_by=args.make_vocab_size_divisible_by,
        tensor_parallel_size=args.tensor_model_parallel_size,
    )
    # args_to_configs dispatches the bert preset for --model_name bert and
    # applies every CLI override (dtype, dropout, recompute, flash, ...)
    args.model_name = "bert"
    mcfg, pcfg, tcfg, dargs = args_to_configs(args, tokenizer.vocab_size)
    import dataclasses

    binary_head = not args.no_binary_head
    mcfg = dataclasses.replace(mcfg, add_binary_head=binary_head)
    if args.use_checkpoint_args and args.load:
        from megatron_llm_tpu.training.checkpointing import (
            load_model_config_from_checkpoint,
        )

        mcfg = load_model_config_from_checkpoint(args.load, mcfg)
    assert pcfg.pipeline_parallel_size == 1, \
        "encoder pretraining: pp>1 not supported (GPT-only pipeline)"

    assert pcfg.context_parallel_size == 1, (
        "--context_parallel_size: ring attention is causal-only; "
        "encoder pretraining doesn't support cp"
    )
    initialize_parallel(
        dp=pcfg.data_parallel_size, pp=1, tp=pcfg.tensor_parallel_size,
        sequence_parallel=pcfg.sequence_parallel,
    )
    model = BertModel(mcfg)

    train_iters = tcfg.train_iters or 0
    num_samples = train_iters * tcfg.global_batch_size
    train_ds, valid_ds, _ = build_train_valid_test_datasets(
        dargs.data_path, dargs.split,
        [num_samples, tcfg.eval_iters * tcfg.global_batch_size, 0],
        mcfg.seq_length, args.masked_lm_prob, args.short_seq_prob,
        tcfg.seed, tokenizer, dataset_type="standard_bert",
        binary_head=binary_head,
    )
    trainer = Trainer(model, tcfg, pcfg, batch_builder=get_batch)
    state = trainer.setup()
    # multi-host: each process loads only its data-axis rows
    row_range = None
    if trainer.ctx is not None and jax.process_count() > 1:
        from megatron_llm_tpu.parallel.multihost import process_row_range

        row_range = process_row_range(
            trainer.ctx, tcfg.micro_batch_size * pcfg.data_parallel_size
        )
    trainer.train_data_iterator = build_pretraining_data_loader(
        train_ds, state.consumed_train_samples, tcfg.micro_batch_size,
        pcfg.data_parallel_size, trainer.num_microbatches_calc.get,
        keys=BERT_KEYS,
        row_range=row_range,
    )
    trainer.valid_data_iterator = build_pretraining_data_loader(
        valid_ds, 0, tcfg.micro_batch_size, pcfg.data_parallel_size, 1,
        keys=BERT_KEYS,
        row_range=row_range,
    )
    state = trainer.train(state)
    if tcfg.save:
        trainer._save(state)


if __name__ == "__main__":
    main()
