#!/usr/bin/env python
"""Golden-logit correctness gate: native model vs side-by-side HuggingFace.

The rebuild of ref verify_correctness.py:107-122 — runs both
implementations on the same batches and prints per-iteration max/avg
absolute logit error and the loss delta. Gate: avg max-abs logit error
<= --tolerance (1e-3 fp32, the reference's own test gate,
ref: tests/test_llama_weights.py:104-106; docs allow 0.01 fp32 / 0.1 fp16,
docs/guide/getting_started.md:152).

With --hf_dir it verifies a real checkpoint; without, it builds a randomly
initialized small HF model (same code path transformers uses for the real
one) so the gate runs hermetically in CI.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", choices=["llama", "falcon"], default="llama")
    p.add_argument("--hf_dir", default=None,
                   help="HF checkpoint dir; omit for a random hermetic model")
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--batch_size", type=int, default=2)
    p.add_argument("--seq_length", type=int, default=64)
    p.add_argument("--tolerance", type=float, default=1e-3)
    # hermetic-model architecture knobs
    p.add_argument("--num_layers", type=int, default=4)
    p.add_argument("--hidden_size", type=int, default=128)
    p.add_argument("--num_heads", type=int, default=8)
    p.add_argument("--num_kv_heads", type=int, default=4)
    p.add_argument("--vocab_size", type=int, default=512)
    p.add_argument("--dump_layer_errors", action="store_true",
                   help="per-layer hidden-state max-abs error vs HF on the "
                        "first batch — localizes drift to the layer that "
                        "introduces it (release-gate debugging aid)")
    args = p.parse_args()

    import torch
    from transformers import AutoModelForCausalLM, LlamaConfig, LlamaForCausalLM

    import jax
    import jax.numpy as jnp

    # Correctness gates compare against torch's true-fp32 matmuls. JAX's
    # default matmul precision lowers fp32 matmul inputs (bf16-class passes;
    # ~1e-3 relative error per matmul on both CPU and TPU), which compounds
    # with depth — a 4-layer/h128 model drifts to ~6e-3 max-abs logit error.
    # Pin the highest precision so an fp32 run is actually fp32; this is the
    # analogue of the reference running its gate in full torch fp32
    # (ref: tests/test_llama_weights.py:104-106).
    jax.config.update("jax_default_matmul_precision", "highest")

    from megatron_llm_tpu.convert import hf_falcon_to_native, hf_llama_to_native
    from megatron_llm_tpu.models import FalconModel, LlamaModel
    from tools.convert_weights import _model_cfg_from_hf

    if args.hf_dir:
        hf = AutoModelForCausalLM.from_pretrained(
            args.hf_dir, torch_dtype=torch.float32
        ).eval()
    elif args.model == "llama":
        hf = LlamaForCausalLM(LlamaConfig(
            vocab_size=args.vocab_size, hidden_size=args.hidden_size,
            intermediate_size=int(args.hidden_size * 8 / 3 // 16 * 16),
            num_hidden_layers=args.num_layers,
            num_attention_heads=args.num_heads,
            num_key_value_heads=args.num_kv_heads,
            max_position_embeddings=max(2048, args.seq_length),
            tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
        )).float().eval()
    else:
        # hermetic falcon: --num_kv_heads 1 builds the 7b MQA style,
        # >1 the 40b grouped (new_decoder_architecture) style — both
        # converter layouts get exercised
        from transformers import FalconConfig, FalconForCausalLM

        mqa = args.num_kv_heads == 1
        hf = FalconForCausalLM(FalconConfig(
            vocab_size=args.vocab_size, hidden_size=args.hidden_size,
            num_hidden_layers=args.num_layers,
            num_attention_heads=args.num_heads,
            num_kv_heads=args.num_kv_heads,
            multi_query=mqa, new_decoder_architecture=not mqa,
            parallel_attn=True, bias=False, alibi=False,
        )).float().eval()

    cfg = _model_cfg_from_hf(args.model, hf.config, "float32")
    import dataclasses
    cfg = dataclasses.replace(cfg, compute_dtype=jnp.float32)
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    convert = hf_llama_to_native if args.model == "llama" else hf_falcon_to_native
    params = jax.tree.map(jnp.asarray, convert(sd, cfg))
    model = (LlamaModel if args.model == "llama" else FalconModel)(cfg)

    def dump_layer_errors(tokens):
        """Per-layer hidden-state drift vs HF (embedding + each block),
        running the native stack layer by layer."""
        from megatron_llm_tpu.models.language_model import embed_tokens
        from megatron_llm_tpu.models.rope import precompute_rope
        from megatron_llm_tpu.models.transformer import transformer_layer

        with torch.no_grad():
            hf_states = hf(torch.tensor(tokens),
                           output_hidden_states=True).hidden_states
        rope = None
        if cfg.position_embedding_type == "rotary":
            rope = precompute_rope(cfg.head_dim, cfg.max_position_embeddings,
                                   cfg.rope_theta, cfg.rope_scaling_factor)
        from megatron_llm_tpu.models.norms import apply_norm

        h = embed_tokens(params, cfg, jnp.asarray(tokens))
        for i in range(cfg.num_layers + 1):
            if i > 0:
                layer_p = jax.tree.map(lambda x: x[i - 1], params["layers"])
                h, _ = transformer_layer(layer_p, cfg, h, rope, None, None)
            # transformers' LAST hidden state is post-final-norm
            h_cmp = (apply_norm(h, params["final_norm"], cfg)
                     if i == cfg.num_layers else h)
            err = float(np.abs(
                np.asarray(h_cmp, np.float32) - hf_states[i].numpy()
            ).max())
            name = "embedding" if i == 0 else f"layer {i - 1}"
            if i == cfg.num_layers:
                name += " (+final norm)"
            print(f"  {name:>22s}: max abs hidden error {err:.3e}",
                  flush=True)

    fwd = jax.jit(lambda p, t: model.forward(p, t)[0])
    rs = np.random.RandomState(0)
    max_errs, ok = [], True
    for it in range(args.iters):
        data = rs.randint(
            0, min(cfg.padded_vocab_size, hf.config.vocab_size),
            (args.batch_size, args.seq_length + 1),
        )
        tokens, labels = data[:, :-1], data[:, 1:]
        with torch.no_grad():
            out = hf(torch.tensor(tokens)).logits
            ref_loss = torch.nn.functional.cross_entropy(
                out.reshape(-1, out.shape[-1]),
                torch.tensor(labels).reshape(-1),
            ).item()
        ref_logits = out.numpy()
        ours_logits = np.asarray(fwd(params, jnp.asarray(tokens)))[
            ..., : ref_logits.shape[-1]
        ]
        our_loss = float(model.loss(
            params, jnp.asarray(tokens), jnp.asarray(labels)
        ))
        abs_err = np.abs(ours_logits - ref_logits)
        max_err, avg_err = float(abs_err.max()), float(abs_err.mean())
        max_errs.append(max_err)
        if args.dump_layer_errors and it == 0:
            dump_layer_errors(tokens)
        # ref verify_correctness.py prints this exact breakdown per iter
        print(
            f"iteration {it}: max abs logit error {max_err:.3e} | "
            f"avg abs logit error {avg_err:.3e} | "
            f"our loss {our_loss:.6f} | hf loss {ref_loss:.6f} | "
            f"loss delta {abs(our_loss - ref_loss):.3e}",
            flush=True,
        )

    avg_max = float(np.mean(max_errs))
    ok = avg_max <= args.tolerance
    print(f"avg max-abs logit error over {args.iters} iters: {avg_max:.3e} "
          f"({'OK' if ok else 'FAIL'}, tolerance {args.tolerance})", flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
