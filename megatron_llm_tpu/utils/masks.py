"""Mask/position-id construction (ref: megatron/utils.py:137-196
`get_ltor_masks_and_position_ids`)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def get_document_starts(tokens: jnp.ndarray, eod_token: int) -> jnp.ndarray:
    """(b, s) int32: for each position, the index of its document's FIRST
    token (documents delimited by eod; the eod token belongs to its
    document). The --reset_attention_mask block-diagonal-causal mask is
    exactly `allowed(i, j) <=> doc_start[i] <= j <= i`, so this one vector
    carries the packed-document mask in O(s) — what ring attention ships
    per sequence shard instead of an O(s^2) dense mask
    (ref: utils.py:137-196)."""
    b, s = tokens.shape
    is_eod = (tokens == eod_token).astype(jnp.int32)
    idx = jnp.arange(s)[None, :]
    boundary = jnp.where(
        jnp.pad(is_eod[:, :-1], ((0, 0), (1, 0))) == 1, idx, 0
    )
    return jax.lax.cummax(boundary, axis=1).astype(jnp.int32)


def get_ltor_masks_and_position_ids(
    tokens: jnp.ndarray,  # (b, s) int
    eod_token: Optional[int] = None,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
) -> Tuple[Optional[jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Returns (attention_mask, loss_mask, position_ids).

    attention_mask is (b, 1, s, s) boolean, True = masked out — or
    **None** whenever the mask is plain causal, i.e. when
    `reset_attention_mask=False` (with or without `reset_position_ids`).
    `None` means "causal" to every attention consumer in this repo and
    keeps the flash / decode kernel paths eligible; callers that index
    the returned mask must handle it. NOTE this is an exported-API
    departure from the reference, which always materializes the dense
    (b, 1, s, s) tensor (ref: utils.py:137-196) — external callers
    porting reference scripts should pass the None straight through to
    `attention_mask=` or rebuild a dense mask with
    `models.attention.causal_mask(s)` (see docs/GUIDE.md, "Masks").

    EOD-reset variants are built vectorised (the reference loops over
    batch in Python, ref: utils.py:162-191); document boundaries are
    where tokens == eod.
    """
    b, s = tokens.shape
    rows = jnp.arange(s)[:, None]
    cols = jnp.arange(s)[None, :]
    loss_mask = jnp.ones((b, s), jnp.float32)
    if eod_mask_loss and eod_token is not None:
        loss_mask = jnp.where(tokens == eod_token, 0.0, loss_mask)

    if not (reset_position_ids or reset_attention_mask):
        position_ids = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        return None, loss_mask, position_ids

    assert eod_token is not None
    is_eod = (tokens == eod_token).astype(jnp.int32)  # (b, s)
    # doc_id[t] = number of EODs strictly before t
    doc_id = jnp.cumsum(is_eod, axis=1) - is_eod  # eod token belongs to its doc

    if reset_position_ids:
        # position within current document: t - index_of_last_boundary
        idx = jnp.arange(s)[None, :]
        # boundary position b_t = largest j <= t with eod at j-1 (or 0)
        boundary = jnp.where(jnp.pad(is_eod[:, :-1], ((0, 0), (1, 0))) == 1, idx, 0)
        start = jax.lax.cummax(boundary, axis=1)
        position_ids = idx - start
    else:
        position_ids = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))

    if reset_attention_mask:
        causal = cols > rows  # (s, s), True = masked
        same_doc = doc_id[:, :, None] == doc_id[:, None, :]  # (b, s, s)
        mask = (~same_doc) | causal[None]
        return mask[:, None], loss_mask, position_ids
    # position reset WITHOUT attention reset keeps plain causal masking:
    # return None so the flash path stays eligible
    return None, loss_mask, position_ids
