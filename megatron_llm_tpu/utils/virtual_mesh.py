"""Virtual multi-device CPU provisioning (shared by tests/conftest.py and
__graft_entry__.dryrun_multichip).

JAX can emulate an n-device mesh on one host with
--xla_force_host_platform_device_count — the capability that lets this
framework test TP/PP/DP collectives anywhere, where the reference needs
>= 2 physical GPUs (SURVEY.md §4). This module must stay import-safe
before jax initializes (no jax imports).
"""

from __future__ import annotations

import re
from typing import MutableMapping, Optional


def force_virtual_cpu_devices(
    n: int, env: Optional[MutableMapping[str, str]] = None
) -> MutableMapping[str, str]:
    """Set the env vars that force an n-device virtual CPU platform.

    Mutates and returns `env` (os.environ or a subprocess env copy). Must
    take effect before the jax backend initializes; in-process callers
    should additionally run jax.config.update("jax_platforms", "cpu")
    because the axon sitecustomize sets jax_platforms=axon,cpu at
    interpreter start.
    """
    if env is None:
        import os

        env = os.environ
    # Disable the axon TPU plugin (its sitecustomize registers the TPU
    # whenever PALLAS_AXON_POOL_IPS is set, overriding JAX_PLATFORMS).
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    # replace any pre-existing device-count flag rather than appending
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    return env
