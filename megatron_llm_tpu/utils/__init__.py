from megatron_llm_tpu.utils.masks import get_ltor_masks_and_position_ids  # noqa: F401
