"""T5 encoder-decoder model.

Parity target: ref megatron/model/t5_model.py:70-198 (`T5Model`,
`T5LMHead`) plus the decoder layer structure of
transformer.py:695-817 with layer_type=decoder:

    h = h + self_attn(input_norm(h))          (causal+padding mask)
    h = h + cross_attn(post_attention_norm(h), encoder_out)
    h = h + mlp(post_cross_norm(h))

Shared word-embedding table between encoder and decoder (the reference's
initialize_word_embeddings), learned absolute positions on both sides,
logits tied to the embedding plus a vocab bias (T5LMHead :40-67). Masks
enter as 2D keep-masks and the 4D forms are built here
(ref: t5_extended_attention_mask :21-27 over the dataset's
make_attention_mask products, t5_dataset.py:91-99).

The decoder is a scan over stacked decoder layers, same compile-once
design as the GPT stack.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import ModelConfig
from megatron_llm_tpu.models.attention import (
    attention_block,
    cross_attention_block,
    padding_mask_2d,
)
from megatron_llm_tpu.models.language_model import (
    embed_tokens,
    init_language_model_params,
)
from megatron_llm_tpu.models.norms import apply_norm
from megatron_llm_tpu.models.transformer import (
    init_layer_params,
    init_norm_params,
    mlp_block,
    transformer_stack,
)
from megatron_llm_tpu.parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from megatron_llm_tpu.parallel.mesh import shard_activation


def _normal(key, shape, std, dtype):
    return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


def init_decoder_layer_params(cfg, key, num_layers: Optional[int] = None):
    """Stacked decoder layers: self-attn params (from the shared init)
    plus cross-attention (wq / fused wkv / wo) and a third norm."""
    L = num_layers if num_layers is not None else cfg.num_layers
    layers = init_layer_params(cfg, key, num_layers=L)
    h, d = cfg.hidden_size, cfg.head_dim
    g, qpk = cfg.num_query_groups, cfg.q_per_kv
    std = cfg.init_method_std
    out_std = (std / jnp.sqrt(2.0 * cfg.num_layers)
               if cfg.use_scaled_init_method else std)
    k1, k2, k3 = jax.random.split(jax.random.fold_in(key, 99), 3)
    dt = cfg.params_dtype
    cross = {
        "wq": _normal(k1, (L, h, g * qpk * d), std, dt),
        "wkv": _normal(k2, (L, h, g * 2 * d), std, dt),
        "wo": _normal(k3, (L, g * qpk * d, h), out_std, dt),
    }
    if cfg.use_bias:
        cross["bq"] = jnp.zeros((L, g * qpk * d), dt)
        cross["bkv"] = jnp.zeros((L, g * 2 * d), dt)
        cross["bo"] = jnp.zeros((L, h), dt)
    layers["cross_attention"] = cross
    layers["post_cross_norm"] = init_norm_params(cfg, (L,))
    return layers


def decoder_stack(layer_params, cfg, hidden, encoder_output, self_mask,
                  cross_mask, dropout_rng=None, deterministic=True):
    """Scan the stacked decoder layers (ref: ParallelTransformer with
    layer_type=decoder, transformer.py:695-817)."""

    def body(carry, xs):
        (h,) = carry
        p, idx = xs
        if dropout_rng is not None:
            rng = jax.random.fold_in(dropout_rng, idx)
            r1, r2, r3 = jax.random.split(rng, 3)
        else:
            r1 = r2 = r3 = None
        # self attention (causal + padding)
        normed = apply_norm(h, p["input_norm"], cfg)
        attn_out, _ = attention_block(
            p["attention"], cfg, normed, None, self_mask, None, r1,
            deterministic, None,
        )
        h = h + attn_out
        # cross attention over the encoder output
        normed = apply_norm(h, p["post_attention_norm"], cfg)
        h = h + cross_attention_block(
            p["cross_attention"], cfg, normed, encoder_output, cross_mask,
            r2, deterministic,
        )
        # mlp
        normed = apply_norm(h, p["post_cross_norm"], cfg)
        h = h + mlp_block(p["mlp"], cfg, normed, r3, deterministic)
        h = shard_activation(h, "hidden")
        return (h,), None

    # same named-savepoint policy ladder as the decoder-only stack
    # (models/remat.py); the cross-attention projections carry the shared
    # save-point names so selective/offload cover T5 too
    from megatron_llm_tpu.models.remat import remat_wrap

    body = remat_wrap(body, cfg.resolved_remat_policy)
    L = jax.tree_util.tree_leaves(layer_params)[0].shape[0]
    (hidden,), _ = jax.lax.scan(body, (hidden,),
                                (layer_params, jnp.arange(L)))
    return hidden




class T5Model:
    """ref: T5Model t5_model.py:70-198."""

    def __init__(self, cfg: ModelConfig):
        assert cfg.position_embedding_type == "absolute", \
            "megatron T5 uses learned absolute positions"
        assert cfg.tie_embed_logits, "T5 LM head ties to word embeddings"
        self.cfg = cfg

    def init(self, rng: jax.Array) -> dict:
        cfg = self.cfg
        params = init_language_model_params(cfg, rng)
        k_dec = jax.random.fold_in(rng, 23)
        params["decoder_layers"] = init_decoder_layer_params(cfg, k_dec)
        params["decoder_final_norm"] = init_norm_params(cfg)
        # T5LMHead vocab bias (ref :55-58)
        params["lm_head_bias"] = jnp.zeros((cfg.padded_vocab_size,),
                                           cfg.params_dtype)
        return params

    def forward(
        self,
        params: dict,
        encoder_input_ids: jnp.ndarray,  # (b, s_e)
        decoder_input_ids: jnp.ndarray,  # (b, s_d)
        encoder_attn_mask: Optional[jnp.ndarray] = None,  # (b, s_e) keep
        decoder_attn_mask: Optional[jnp.ndarray] = None,  # (b, s_d) keep
        dropout_rng=None,
        deterministic: bool = True,
        enc_hidden_states: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Returns (lm_logits (b, s_d, V), encoder_output (b, s_e, h))
        (ref: T5Model.forward :121-166)."""
        cfg = self.cfg
        b, s_e = encoder_input_ids.shape
        s_d = decoder_input_ids.shape[1]
        if encoder_attn_mask is None:
            encoder_attn_mask = jnp.ones((b, s_e), jnp.int32)
        if decoder_attn_mask is None:
            decoder_attn_mask = jnp.ones((b, s_d), jnp.int32)

        if dropout_rng is not None:
            r_enc_e, r_enc, r_dec_e, r_dec = jax.random.split(dropout_rng, 4)
        else:
            r_enc_e = r_enc = r_dec_e = r_dec = None

        # ---- encoder (padding mask) ----------------------------------
        if enc_hidden_states is None:
            enc_mask = padding_mask_2d(encoder_attn_mask)
            enc_h = embed_tokens(params, cfg, encoder_input_ids, None,
                                 r_enc_e, deterministic)
            enc_h, _ = transformer_stack(
                params["layers"], cfg, enc_h, None, enc_mask, None,
                r_enc, deterministic,
            )
            enc_out = apply_norm(enc_h, params["final_norm"], cfg)
        else:
            enc_out = enc_hidden_states

        # ---- decoder (causal+padding self mask, enc-dec cross mask) ---
        causal = jnp.tril(jnp.ones((s_d, s_d), jnp.float32))
        dec_keep = decoder_attn_mask.astype(jnp.float32)
        self_keep = (dec_keep[:, :, None] * dec_keep[:, None, :]
                     * causal[None])
        self_mask = (self_keep < 0.5)[:, None]
        cross_mask = padding_mask_2d(decoder_attn_mask, encoder_attn_mask)

        dec_h = embed_tokens(params, cfg, decoder_input_ids, None, r_dec_e,
                             deterministic)
        dec_h = decoder_stack(
            params["decoder_layers"], cfg, dec_h, enc_out, self_mask,
            cross_mask, r_dec, deterministic,
        )
        dec_h = apply_norm(dec_h, params["decoder_final_norm"], cfg)

        emb = params["embedding"]["word_embeddings"].astype(cfg.compute_dtype)
        logits = dec_h @ emb.T + params["lm_head_bias"].astype(
            cfg.compute_dtype
        )
        return shard_activation(logits, "logits"), enc_out

    def loss(
        self,
        params: dict,
        encoder_input_ids: jnp.ndarray,
        decoder_input_ids: jnp.ndarray,
        lm_labels: jnp.ndarray,  # (b, s_d)
        loss_mask: Optional[jnp.ndarray] = None,
        encoder_attn_mask: Optional[jnp.ndarray] = None,
        decoder_attn_mask: Optional[jnp.ndarray] = None,
        dropout_rng=None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        """Masked mean CE over decoder positions (ref: loss_func
        pretrain_t5.py:76-85)."""
        logits, _ = self.forward(
            params, encoder_input_ids, decoder_input_ids,
            encoder_attn_mask, decoder_attn_mask, dropout_rng, deterministic,
        )
        losses = vocab_parallel_cross_entropy(logits, lm_labels)
        if loss_mask is None:
            return jnp.mean(losses)
        lm = loss_mask.astype(jnp.float32)
        return jnp.sum(losses * lm) / jnp.maximum(jnp.sum(lm), 1.0)
