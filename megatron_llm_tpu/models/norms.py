"""LayerNorm / RMSNorm with fp32 statistics.

Parity targets: ref megatron/model/fused_layer_norm.py —
`MixedFusedLayerNorm` (:64, CUDA kernel with fp32 stats) and pure-python
`RMSNorm` (:125-139, fp32 compute then cast, weight applied after the cast).
On TPU the fused path is a Pallas kernel (ops/rmsnorm.py); these jnp
versions are the always-correct XLA-fused reference implementations.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm: fp32 normalize, cast back, then scale (ref: fused_layer_norm.py:133-138)."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = (x32 * lax.rsqrt(var + eps)).astype(x.dtype)
    return normed * scale.astype(x.dtype)


def layer_norm(
    x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5
) -> jnp.ndarray:
    """Affine LayerNorm with fp32 statistics (ref: layer_norm_cuda semantics)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    normed = ((x32 - mean) * lax.rsqrt(var + eps)).astype(x.dtype)
    return normed * scale.astype(x.dtype) + bias.astype(x.dtype)


def apply_norm(x: jnp.ndarray, norm_params: dict, cfg) -> jnp.ndarray:
    """Dispatch on config (ref: transformer.py chooses RMSNorm vs LayerNorm).

    use_fused_rmsnorm routes through the Pallas kernel (ops/rmsnorm.py) —
    the analogue of the reference routing norms through apex's fused CUDA
    kernels (fused_layer_norm.py:64)."""
    if cfg.use_rms_norm:
        if getattr(cfg, "use_fused_rmsnorm", False):
            from megatron_llm_tpu.ops.rmsnorm import fused_rms_norm

            return fused_rms_norm(x, norm_params["scale"],
                                  cfg.layernorm_epsilon)
        return rms_norm(x, norm_params["scale"], cfg.layernorm_epsilon)
    return layer_norm(
        x, norm_params["scale"], norm_params["bias"], cfg.layernorm_epsilon
    )
