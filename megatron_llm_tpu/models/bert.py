"""BERT model: bidirectional encoder + masked-LM head + binary (SOP) head.

Parity target: ref megatron/model/bert_model.py:125-242 (`BertModel`,
`BertLMHead`, `post_language_model_processing`) and the pooler
(language_model.py:97-130). Structure:

- padding (non-causal) attention from the 2D keep-mask's outer product
  (ref: bert_extended_attention_mask :21-35);
- learned absolute positions + tokentype (segment) embeddings;
- pooler: tanh(dense(hidden[:, 0])) feeding the 2-way binary head
  (NSP/SOP, ref: Pooler language_model.py:97-130);
- BertLMHead: dense -> gelu -> layernorm -> logits against the TIED word
  embedding table plus a vocab bias (ref: BertLMHead :47-92).

The reference runs this through the same ParallelTransformer as GPT; here
it is the same transformer_stack — post/pre-LN, biases, gelu all come
from the shared config.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import ModelConfig
from megatron_llm_tpu.models.activations import ACTIVATIONS
from megatron_llm_tpu.models.attention import padding_mask_2d
from megatron_llm_tpu.models.language_model import (
    embed_tokens,
    init_language_model_params,
)
from megatron_llm_tpu.models.norms import apply_norm, layer_norm
from megatron_llm_tpu.models.transformer import transformer_stack
from megatron_llm_tpu.parallel.cross_entropy import (
    cross_entropy,
    vocab_parallel_cross_entropy,
)
from megatron_llm_tpu.parallel.mesh import shard_activation


def _normal(key, shape, std, dtype):
    return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


class BertModel:
    """ref: BertModel bert_model.py:125-242."""

    def __init__(self, cfg: ModelConfig):
        assert cfg.position_embedding_type == "absolute", \
            "BERT uses learned absolute positions (ref bert_model.py:183)"
        assert cfg.tie_embed_logits, "BERT LM head ties to word embeddings"
        self.cfg = cfg

    def init(self, rng: jax.Array) -> dict:
        cfg = self.cfg
        params = init_language_model_params(cfg, rng)
        k1, k2, k3, k4 = jax.random.split(jax.random.fold_in(rng, 17), 4)
        std, dt, h = cfg.init_method_std, cfg.params_dtype, cfg.hidden_size
        # BertLMHead (ref :47-92): dense + LN + vocab bias
        params["lm_head"] = {
            "dense_w": _normal(k1, (h, h), std, dt),
            "dense_b": jnp.zeros((h,), dt),
            "norm": {"scale": jnp.ones((h,), dt),
                     "bias": jnp.zeros((h,), dt)},
            "bias": jnp.zeros((cfg.padded_vocab_size,), dt),
        }
        if cfg.add_binary_head:
            # pooler (language_model.py:97-130) + 2-way head (:176-180)
            params["pooler"] = {
                "w": _normal(k2, (h, h), std, dt),
                "b": jnp.zeros((h,), dt),
            }
            params["binary_head"] = {
                "w": _normal(k3, (h, 2), std, dt),
                "b": jnp.zeros((2,), dt),
            }
        return params

    def encode(self, params, tokens, attention_mask=None, tokentype_ids=None,
               dropout_rng=None, deterministic=True) -> jnp.ndarray:
        """Run the bidirectional encoder -> (b, s, h) final hidden."""
        cfg = self.cfg
        b, s = tokens.shape
        if attention_mask is None:
            attention_mask = jnp.ones((b, s), jnp.int32)
        mask4 = padding_mask_2d(attention_mask)

        if dropout_rng is not None:
            emb_rng, stack_rng = jax.random.split(dropout_rng)
        else:
            emb_rng = stack_rng = None
        hidden = embed_tokens(params, cfg, tokens, None, emb_rng,
                              deterministic, tokentype_ids=tokentype_ids)
        hidden, _ = transformer_stack(
            params["layers"], cfg, hidden, None, mask4, None,
            stack_rng, deterministic,
        )
        return apply_norm(hidden, params["final_norm"], cfg)

    def forward(
        self,
        params: dict,
        tokens: jnp.ndarray,  # (b, s)
        attention_mask: Optional[jnp.ndarray] = None,  # (b, s) keep-mask
        tokentype_ids: Optional[jnp.ndarray] = None,
        dropout_rng=None,
        deterministic: bool = True,
    ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        """Returns (lm_logits (b, s, V), binary_logits (b, 2) | None)
        (ref: BertModel.forward :178-205)."""
        cfg = self.cfg
        hidden = self.encode(params, tokens, attention_mask, tokentype_ids,
                             dropout_rng, deterministic)

        # BertLMHead (ref :83-92)
        lh = params["lm_head"]
        dt = cfg.compute_dtype
        x = hidden @ lh["dense_w"].astype(dt) + lh["dense_b"].astype(dt)
        x = ACTIVATIONS["gelu"](x)
        x = layer_norm(x, lh["norm"]["scale"], lh["norm"]["bias"],
                       cfg.layernorm_epsilon)
        emb = params["embedding"]["word_embeddings"].astype(dt)
        logits = x @ emb.T + lh["bias"].astype(dt)
        logits = shard_activation(logits, "logits")

        binary_logits = None
        if cfg.add_binary_head:
            pooled = jnp.tanh(
                hidden[:, 0] @ params["pooler"]["w"].astype(dt)
                + params["pooler"]["b"].astype(dt)
            )
            binary_logits = (
                pooled @ params["binary_head"]["w"].astype(dt)
                + params["binary_head"]["b"].astype(dt)
            )
        return logits, binary_logits

    def loss(
        self,
        params: dict,
        tokens: jnp.ndarray,
        labels: jnp.ndarray,  # (b, s) masked-LM targets
        loss_mask: Optional[jnp.ndarray] = None,
        attention_mask: Optional[jnp.ndarray] = None,
        tokentype_ids: Optional[jnp.ndarray] = None,
        sop_labels: Optional[jnp.ndarray] = None,  # (b,) 0/1
        dropout_rng=None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        """lm_loss + sop_loss (ref: loss_func pretrain_bert.py:71-91 —
        both terms are masked/plain means, summed)."""
        logits, binary_logits = self.forward(
            params, tokens, attention_mask, tokentype_ids, dropout_rng,
            deterministic,
        )
        losses = vocab_parallel_cross_entropy(logits, labels)
        if loss_mask is None:
            lm_loss = jnp.mean(losses)
        else:
            lm = loss_mask.astype(jnp.float32)
            lm_loss = jnp.sum(losses * lm) / jnp.maximum(jnp.sum(lm), 1.0)
        if binary_logits is not None and sop_labels is not None:
            sop_losses = cross_entropy(binary_logits.astype(jnp.float32),
                                       sop_labels)
            return lm_loss + jnp.mean(sop_losses)
        return lm_loss
