"""Biencoder (ICT / retriever) model: two BERT towers + retrieval loss.

Parity target: ref megatron/model/biencoder_model.py —
`PretrainedBertModel` (:255-320: CLS-token pooling + optional projection)
and `BiEncoderModel` (:71-160: query tower + context tower, optionally
shared). The ICT pretraining loss is in-batch softmax retrieval
(ref: pretrain_ict.py:68-86: query·contextᵀ logits, diagonal targets).

Functionally the towers are the shared BertModel's encoder; parameters
are {"query": <bert params>, "context": <bert params>} or a single
{"shared": ...} tree, plus optional projection matrices.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import ModelConfig
from megatron_llm_tpu.models.bert import BertModel
from megatron_llm_tpu.parallel.cross_entropy import cross_entropy


class BiEncoderModel:
    """ref: BiEncoderModel biencoder_model.py:71-160."""

    def __init__(self, cfg: ModelConfig, projection_dim: int = 0,
                 shared_query_context_model: bool = False):
        # towers are headless BERT encoders
        self.cfg = cfg
        self.projection_dim = projection_dim
        self.shared = shared_query_context_model
        self.bert = BertModel(cfg)

    def _init_tower(self, rng):
        params = self.bert.init(rng)
        # towers carry no LM/binary heads
        params.pop("lm_head", None)
        params.pop("binary_head", None)
        params.pop("pooler", None)
        if self.projection_dim > 0:
            params["projection_enc"] = {
                "w": (jax.random.normal(
                    jax.random.fold_in(rng, 5),
                    (self.cfg.hidden_size, self.projection_dim), jnp.float32,
                ) * self.cfg.init_method_std).astype(self.cfg.params_dtype),
                "b": jnp.zeros((self.projection_dim,),
                               self.cfg.params_dtype),
            }
        return params

    def init(self, rng: jax.Array) -> dict:
        if self.shared:
            return {"shared": self._init_tower(rng)}
        kq, kc = jax.random.split(rng)
        return {"query": self._init_tower(kq),
                "context": self._init_tower(kc)}

    def embed_text(self, tower_params, tokens, attention_mask=None,
                   tokentype_ids=None, dropout_rng=None,
                   deterministic=True) -> jnp.ndarray:
        """CLS-token embedding, optionally projected
        (ref: PretrainedBertModel.forward :297-319)."""
        hidden = self.bert.encode(tower_params, tokens, attention_mask,
                                  tokentype_ids, dropout_rng, deterministic)
        pooled = hidden[:, 0]
        if self.projection_dim > 0:
            pooled = (
                pooled @ tower_params["projection_enc"]["w"].astype(
                    self.cfg.compute_dtype
                )
                + tower_params["projection_enc"]["b"].astype(
                    self.cfg.compute_dtype
                )
            )
        return pooled

    def forward(
        self,
        params: dict,
        query_tokens: jnp.ndarray,
        query_attention_mask: Optional[jnp.ndarray],
        query_types: Optional[jnp.ndarray],
        context_tokens: jnp.ndarray,
        context_attention_mask: Optional[jnp.ndarray],
        context_types: Optional[jnp.ndarray],
        dropout_rng=None,
        deterministic: bool = True,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(query_logits (b, d), context_logits (b, d))
        (ref: BiEncoderModel.forward :123-143)."""
        qp = params["shared"] if self.shared else params["query"]
        cp = params["shared"] if self.shared else params["context"]
        if dropout_rng is not None:
            rq, rc = jax.random.split(dropout_rng)
        else:
            rq = rc = None
        q = self.embed_text(qp, query_tokens, query_attention_mask,
                            query_types, rq, deterministic)
        c = self.embed_text(cp, context_tokens, context_attention_mask,
                            context_types, rc, deterministic)
        return q, c

    def loss(self, params, query_tokens, query_mask, context_tokens,
             context_mask, dropout_rng=None,
             deterministic: bool = True) -> jnp.ndarray:
        """In-batch retrieval CE: each query's positive is its own block
        (ref: pretrain_ict.py:68-86)."""
        q, c = self.forward(params, query_tokens, query_mask, None,
                            context_tokens, context_mask, None,
                            dropout_rng, deterministic)
        scores = q.astype(jnp.float32) @ c.astype(jnp.float32).T  # (b, b)
        targets = jnp.arange(scores.shape[0])
        return jnp.mean(cross_entropy(scores, targets))
