"""Classification + multiple-choice heads on the BERT encoder.

Parity targets: ref megatron/model/classification.py:17-105 (pooled CLS
-> dropout -> num_classes linear) and multiple_choice.py (same with a
1-dim head over flattened (b * num_choices, s) inputs, reshaped back to
(b, num_choices)). Both reuse BertModel.encode + the pooler; the
downstream GLUE/RACE finetuning in tasks/ drives them.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import ModelConfig
from megatron_llm_tpu.models.bert import BertModel
from megatron_llm_tpu.parallel.cross_entropy import cross_entropy


def _normal(key, shape, std, dtype):
    return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


class Classification:
    """ref: Classification classification.py:17-105."""

    def __init__(self, cfg: ModelConfig, num_classes: int):
        self.cfg = cfg
        self.num_classes = num_classes
        self.bert = BertModel(cfg)

    def init(self, rng: jax.Array) -> dict:
        cfg = self.cfg
        params = self.bert.init(rng)
        params.pop("lm_head", None)
        params.pop("binary_head", None)
        k1, k2 = jax.random.split(jax.random.fold_in(rng, 31))
        h = cfg.hidden_size
        if "pooler" not in params:
            params["pooler"] = {
                "w": _normal(k1, (h, h), cfg.init_method_std,
                             cfg.params_dtype),
                "b": jnp.zeros((h,), cfg.params_dtype),
            }
        params["classification_head"] = {
            "w": _normal(k2, (h, self.num_classes), cfg.init_method_std,
                         cfg.params_dtype),
            "b": jnp.zeros((self.num_classes,), cfg.params_dtype),
        }
        return params

    def forward(self, params, tokens, attention_mask=None,
                tokentype_ids=None, dropout_rng=None,
                deterministic: bool = True) -> jnp.ndarray:
        """(b, s) -> (b, num_classes) logits
        (ref: Classification.forward :58-80)."""
        cfg = self.cfg
        hidden = self.bert.encode(params, tokens, attention_mask,
                                  tokentype_ids, dropout_rng, deterministic)
        dt = cfg.compute_dtype
        pooled = jnp.tanh(
            hidden[:, 0] @ params["pooler"]["w"].astype(dt)
            + params["pooler"]["b"].astype(dt)
        )
        if not deterministic and cfg.hidden_dropout > 0 and dropout_rng is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(dropout_rng, 7),
                1.0 - cfg.hidden_dropout, pooled.shape,
            )
            pooled = pooled * keep / (1.0 - cfg.hidden_dropout)
        head = params["classification_head"]
        return pooled @ head["w"].astype(dt) + head["b"].astype(dt)

    def loss(self, params, tokens, labels, attention_mask=None,
             tokentype_ids=None, dropout_rng=None,
             deterministic: bool = True) -> jnp.ndarray:
        """Mean CE over classes (ref: cross_entropy_loss_func
        tasks/finetune_utils.py:36-46)."""
        logits = self.forward(params, tokens, attention_mask, tokentype_ids,
                              dropout_rng, deterministic)
        return jnp.mean(cross_entropy(logits.astype(jnp.float32), labels))


class MultipleChoice:
    """ref: MultipleChoice multiple_choice.py — a 1-logit head scored per
    choice; inputs carry a leading choices axis."""

    def __init__(self, cfg: ModelConfig, num_choices: int = 4):
        self.cfg = cfg
        self.num_choices = num_choices
        self._cls = Classification(cfg, num_classes=1)

    def init(self, rng: jax.Array) -> dict:
        return self._cls.init(rng)

    def forward(self, params, tokens, attention_mask=None,
                tokentype_ids=None, dropout_rng=None,
                deterministic: bool = True) -> jnp.ndarray:
        """tokens (b, num_choices, s) -> (b, num_choices) logits."""
        b, c, s = tokens.shape
        flat = lambda x: (None if x is None  # noqa: E731
                          else x.reshape(b * c, *x.shape[2:]))
        logits = self._cls.forward(
            params, tokens.reshape(b * c, s), flat(attention_mask),
            flat(tokentype_ids), dropout_rng, deterministic,
        )
        return logits.reshape(b, c)

    def loss(self, params, tokens, labels, attention_mask=None,
             tokentype_ids=None, dropout_rng=None,
             deterministic: bool = True) -> jnp.ndarray:
        logits = self.forward(params, tokens, attention_mask, tokentype_ids,
                              dropout_rng, deterministic)
        return jnp.mean(cross_entropy(logits.astype(jnp.float32), labels))
