"""GPT-family model wrapper (ref: megatron/model/gpt_model.py).

A thin stateless class: holds the config, exposes `init` / `forward` /
`loss`. All state lives in the params pytree so the whole object is safe to
close over in jitted functions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import ModelConfig
from megatron_llm_tpu.models.language_model import (
    chunked_head_cross_entropy,
    init_language_model_params,
    language_model_forward,
)


class GPTModel:
    """ref: GPTModel gpt_model.py:45-124."""

    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        self._check_config()

    def _check_config(self):
        pass

    def init(self, rng: jax.Array) -> dict:
        return init_language_model_params(self.cfg, rng)

    def forward(
        self,
        params: dict,
        tokens: jnp.ndarray,
        position_ids: Optional[jnp.ndarray] = None,
        attention_mask: Optional[jnp.ndarray] = None,
        dropout_rng=None,
        deterministic: bool = True,
        kv_caches: Optional[dict] = None,
    ) -> Tuple[jnp.ndarray, Optional[dict]]:
        """Returns (logits, new_kv_caches) (ref: gpt_model.py:84-100)."""
        return language_model_forward(
            params, self.cfg, tokens, position_ids, attention_mask,
            dropout_rng, deterministic, kv_caches,
        )

    def loss(
        self,
        params: dict,
        tokens: jnp.ndarray,
        labels: jnp.ndarray,
        loss_mask: Optional[jnp.ndarray] = None,
        position_ids: Optional[jnp.ndarray] = None,
        attention_mask: Optional[jnp.ndarray] = None,
        dropout_rng=None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        """Mean masked CE (ref: post_language_model_processing
        gpt_model.py:18-42 + loss_func finetune.py:83-89).

        The head + CE run chunked over the sequence so full (b, s, V)
        logits never materialise (see chunked_head_cross_entropy)."""
        hidden, _ = language_model_forward(
            params, self.cfg, tokens, position_ids, attention_mask,
            dropout_rng, deterministic, return_hidden=True,
        )
        losses = chunked_head_cross_entropy(params, self.cfg, hidden, labels)
        if loss_mask is None:
            return jnp.mean(losses)
        loss_mask = loss_mask.astype(jnp.float32)
        return jnp.sum(losses * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)

    def prepare_decode_params(self, params: dict) -> dict:
        """Decode-layout view of the params: the stacked GLU up/gate
        weight (L, h, 2, f) flattened to (L, h, 2f) — a row-major bitcast
        done ONCE before the decode loop, so every single-token MLP matvec
        streams the weight at full GEMV bandwidth instead of tiling the
        2-sized gate/up axis into sublanes (~33% of HBM bandwidth, traced
        on v5e; mlp_block dispatches on the weight's rank)."""
        if not self.cfg.glu_activation:
            return params
        params = dict(params)
        layers = dict(params["layers"])
        mlp = dict(layers["mlp"])
        w1 = mlp["w1"]
        mlp["w1"] = w1.reshape(w1.shape[0], w1.shape[1], -1)
        layers["mlp"] = mlp
        params["layers"] = layers
        return params

    def init_kv_caches(self, batch_size: int, max_len: int) -> dict:
        """Per-layer stacked KV cache for incremental decode
        (ref: InferenceParams forward_step.py:17-41)."""
        cfg = self.cfg
        shape = (cfg.num_layers, batch_size, max_len, cfg.num_query_groups,
                 cfg.head_dim)
        return {
            "k": jnp.zeros(shape, cfg.compute_dtype),
            "v": jnp.zeros(shape, cfg.compute_dtype),
            "offset": jnp.array(0, jnp.int32),
        }
