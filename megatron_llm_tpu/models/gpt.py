"""GPT-family model wrapper (ref: megatron/model/gpt_model.py).

A thin stateless class: holds the config, exposes `init` / `forward` /
`loss`. All state lives in the params pytree so the whole object is safe to
close over in jitted functions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import ModelConfig
from megatron_llm_tpu.models.language_model import (
    chunked_head_cross_entropy,
    init_language_model_params,
    language_model_forward,
)


class GPTModel:
    """ref: GPTModel gpt_model.py:45-124."""

    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        self._check_config()

    def _check_config(self):
        pass

    def init(self, rng: jax.Array) -> dict:
        return init_language_model_params(self.cfg, rng)

    def forward(
        self,
        params: dict,
        tokens: jnp.ndarray,
        position_ids: Optional[jnp.ndarray] = None,
        attention_mask: Optional[jnp.ndarray] = None,
        dropout_rng=None,
        deterministic: bool = True,
        kv_caches: Optional[dict] = None,
    ) -> Tuple[jnp.ndarray, Optional[dict]]:
        """Returns (logits, new_kv_caches) (ref: gpt_model.py:84-100)."""
        return language_model_forward(
            params, self.cfg, tokens, position_ids, attention_mask,
            dropout_rng, deterministic, kv_caches,
        )

    def loss(
        self,
        params: dict,
        tokens: jnp.ndarray,
        labels: jnp.ndarray,
        loss_mask: Optional[jnp.ndarray] = None,
        position_ids: Optional[jnp.ndarray] = None,
        attention_mask: Optional[jnp.ndarray] = None,
        dropout_rng=None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        """Mean masked CE (ref: post_language_model_processing
        gpt_model.py:18-42 + loss_func finetune.py:83-89).

        The head + CE run chunked over the sequence so full (b, s, V)
        logits never materialise (see chunked_head_cross_entropy)."""
        hidden, _ = language_model_forward(
            params, self.cfg, tokens, position_ids, attention_mask,
            dropout_rng, deterministic, return_hidden=True,
        )
        losses = chunked_head_cross_entropy(params, self.cfg, hidden, labels)
        if loss_mask is None:
            return jnp.mean(losses)
        loss_mask = loss_mask.astype(jnp.float32)
        return jnp.sum(losses * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)

    def loss_terms(
        self,
        params: dict,
        tokens: jnp.ndarray,
        labels: jnp.ndarray,
        loss_mask: Optional[jnp.ndarray] = None,
        position_ids: Optional[jnp.ndarray] = None,
        attention_mask: Optional[jnp.ndarray] = None,
        dropout_rng=None,
        deterministic: bool = True,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """`loss` decomposed into (numerator, denominator) so a caller
        that holds only a DATA-PARALLEL SLICE of the batch can rebuild
        the global loss exactly: both terms are row-additive, so
        psum(num) / max(psum(den), 1) reproduces `loss`'s op chain
        bitwise (the ZeRO-1 explicit reduce-scatter path,
        optimizer/zero1.py, differentiates num/max(global_den, 1) to
        get the identical backward cotangent). The masked form uses the
        exact expressions of `loss`; the unmasked denominator is the
        token count.

        Implemented AS the composition of `loss_pieces` with one
        full-range layer group — the factored pieces are the single
        source of the op chain, so the backward-interleaved overlap
        path (which vjps the pieces group by group) can never drift
        from this function."""
        embed_fn, group_fn, head_fn = self.loss_pieces(
            tokens, labels, loss_mask, position_ids, attention_mask,
            dropout_rng, deterministic,
        )
        aux_params = {k: v for k, v in params.items() if k != "layers"}
        hidden = group_fn(params["layers"], embed_fn(aux_params), 0)
        return head_fn(aux_params, hidden)

    def loss_pieces(
        self,
        tokens: jnp.ndarray,
        labels: jnp.ndarray,
        loss_mask: Optional[jnp.ndarray] = None,
        position_ids: Optional[jnp.ndarray] = None,
        attention_mask: Optional[jnp.ndarray] = None,
        dropout_rng=None,
        deterministic: bool = True,
    ):
        """`loss_terms` factored at layer-group boundaries so a caller
        can run the backward group by group and issue each group's
        gradient collective as its cotangents materialize (the
        backward-interleaved ZeRO-1 reduce-scatter, optimizer/zero1.py,
        ISSUE 12). Returns

          (embed_fn(aux_params) -> hidden0,
           group_fn(layer_slice, hidden, layer_offset) -> hidden,
           head_fn(aux_params, hidden) -> (numerator, denominator))

        where `aux_params` is the params dict WITHOUT "layers" and
        `layer_slice` is a contiguous [lo:hi] slice of the stacked
        layer tree. Composing the pieces reproduces `loss_terms`'s
        exact op chain — same rope table, same emb/stack dropout-rng
        split, same per-layer fold_in keys via `layer_offset`, same
        head/CE expressions — so vjp-by-pieces is the SAME backward
        ops as value_and_grad of `loss_terms` (fp32 bitwise; pinned in
        tests/test_overlap.py)."""
        from megatron_llm_tpu.models.language_model import (
            chunked_head_cross_entropy,
            embed_tokens,
        )
        from megatron_llm_tpu.models.norms import apply_norm
        from megatron_llm_tpu.models.rope import precompute_rope
        from megatron_llm_tpu.models.transformer import transformer_stack

        cfg = self.cfg
        if cfg.position_embedding_type == "rotary":
            rope_table = precompute_rope(
                cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta,
                cfg.rope_scaling_factor,
            )
        else:
            rope_table = None
        if dropout_rng is not None:
            emb_rng, stack_rng = jax.random.split(dropout_rng)
        else:
            emb_rng = stack_rng = None

        def embed_fn(aux_params):
            return embed_tokens(aux_params, cfg, tokens, position_ids,
                                emb_rng, deterministic)

        def group_fn(layer_slice, hidden, layer_offset):
            out, _ = transformer_stack(
                layer_slice, cfg, hidden, rope_table, attention_mask,
                position_ids, stack_rng, deterministic,
                layer_offset=layer_offset,
            )
            return out

        def head_fn(aux_params, hidden):
            hidden = apply_norm(hidden, aux_params["final_norm"], cfg)
            losses = chunked_head_cross_entropy(aux_params, cfg, hidden,
                                                labels)
            if loss_mask is None:
                return jnp.sum(losses), jnp.float32(losses.size)
            lm = loss_mask.astype(jnp.float32)
            return jnp.sum(losses * lm), jnp.sum(lm)

        return embed_fn, group_fn, head_fn

    def loss_denominator(self, tokens=None, labels=None, loss_mask=None,
                         **_) -> jnp.ndarray:
        """The `loss_terms` denominator from mask arithmetic alone (no
        forward pass, no params): what the explicit ZeRO-1 path psums
        BEFORE the backward so the local grad target can divide by the
        global count."""
        if loss_mask is None:
            ref = labels if labels is not None else tokens
            return jnp.float32(ref.size)
        return jnp.sum(loss_mask.astype(jnp.float32))

    def prepare_decode_params(self, params: dict,
                              quantize_int8: bool = False,
                              flatten_glu: bool = True) -> dict:
        """Decode-layout view of the params, built ONCE before the token
        loop (called inside generate's jit, ahead of the while_loop):

        - the stacked (L, ...) layer tree is split into a TUPLE of
          per-layer trees of standalone contiguous arrays. Inside the
          decode loop the layer scan would otherwise dynamic-slice every
          layer's weights into fresh buffers each token — a full extra
          read+write of all layer weights per step (traced on v5e:
          ~95us/layer/step, i.e. the GEMVs paid double their weight
          traffic). transformer_stack unrolls over the tuple;
        - the GLU up/gate weight (h, 2, f) is flattened to (h, 2f) (a
          row-major bitcast): the 2-sized axis otherwise tiles into
          sublanes and the matvec streams at ~33% of HBM bandwidth;
        - `quantize_int8=True` (ISSUE 9, decode-only — the fp tree is
          untouched and stays the default): the four big per-layer GEMV
          weights (wqkv, wo, w1, w2) are one-shot quantized to
          weight-only int8 with per-output-channel fp32 scales
          (ops/quantization.quantize_decode_layers); the decode matvecs
          read half the weight bytes. Biases/norms/embeddings/head stay
          fp — see the accuracy contract in docs/GUIDE.md ("Quantized
          serving");
        - `flatten_glu=False` (ISSUE 14, the tp-sharded serving
          engine): keep the GLU weight in the training (h, 2, f)
          layout. The flat (h, 2f) view concatenates [gate | up] along
          exactly the axis tensor parallelism shards, so a contiguous
          model split would separate gates from ups and force a
          mid-MLP reshard; the unflattened layout shards f per chip
          and keeps the GLU elementwise-local
          (parallel/sharding.decode_param_specs). Single-chip engines
          keep the flatten (the sublane-bandwidth win above).
        """
        import jax

        if quantize_int8 and not flatten_glu:
            raise ValueError(
                "quantize_int8 requires the flattened GLU decode "
                "layout (quantize_decode_layers quantizes the 2D "
                "view); tp-sharded engines serve the fp decode tree")
        L = self.cfg.num_layers
        stacked = params["layers"]

        def layer_slice(i):
            layer = jax.tree.map(lambda x: x[i], stacked)
            if self.cfg.glu_activation and flatten_glu:
                mlp = dict(layer["mlp"])
                w1 = mlp["w1"]
                mlp["w1"] = w1.reshape(w1.shape[0], -1)
                layer = dict(layer)
                layer["mlp"] = mlp
            return layer

        params = dict(params)
        params["layers"] = tuple(layer_slice(i) for i in range(L))
        if quantize_int8:
            from megatron_llm_tpu.ops.quantization import (
                quantize_decode_layers,
            )

            params["layers"] = quantize_decode_layers(params["layers"])
        return params

    def init_kv_caches(self, batch_size: int, max_len: int,
                       layout: str = "stacked") -> dict:
        """KV cache for incremental decode (ref: InferenceParams
        forward_step.py:17-41).

        layout="stacked": one (L, b, T, g, d) pair — what the layer scan
        (and the pp pipelined decode's per-stage shards) carries.
        layout="layers": per-layer standalone (b, g, T, d) arrays for the
        unrolled decode path (see prepare_decode_params) — each layer's
        column update and attention read hit a small buffer in place with
        no per-layer stack slicing, and the (g, T) order makes the
        QK/PV contractions clean (b*g)-batched GEMMs over the T axis.

        Both layouts feed the Pallas decode-attention kernel in place
        (ops/decode_attention.py: "gtd" = layers, "tgd" = a stacked
        layer's slice); a max_len with a power-of-2 factor >= 16 keeps
        the kernel eligible (otherwise the XLA matvecs serve the cache).
        """
        cfg = self.cfg
        if layout == "layers":
            shape = (batch_size, cfg.num_query_groups, max_len,
                     cfg.head_dim)
            return {
                "k_layers": tuple(jnp.zeros(shape, cfg.compute_dtype)
                                  for _ in range(cfg.num_layers)),
                "v_layers": tuple(jnp.zeros(shape, cfg.compute_dtype)
                                  for _ in range(cfg.num_layers)),
                "offset": jnp.array(0, jnp.int32),
            }
        assert layout == "stacked", layout
        shape = (cfg.num_layers, batch_size, max_len, cfg.num_query_groups,
                 cfg.head_dim)
        return {
            "k": jnp.zeros(shape, cfg.compute_dtype),
            "v": jnp.zeros(shape, cfg.compute_dtype),
            "offset": jnp.array(0, jnp.int32),
        }

    def init_paged_kv_caches(self, slots: int, num_pages: int,
                             page_size: int,
                             max_pages_per_slot: int,
                             kv_dtype=None,
                             mesh_ctx=None) -> dict:
        """Paged KV cache for the continuous-batching engine
        (inference/engine.py): per-layer GLOBAL page pools
        (num_pages, page_size, g, d) shared by all slots, one
        (slots, max_pages_per_slot) page table mapping each slot's
        logical pages to pool indices, and per-slot valid lengths.
        Pool page 0 is the NULL page (never allocated): fresh/retired
        slots point every table entry at it, so clamped kernel DMAs and
        inactive-slot writes always land on a real — but dead — page.
        HBM cost per layer: 2 * num_pages * page_size * g * d *
        itemsize; unlike the dense layouts above it is independent of
        slots * max_len, which is the whole point (docs/GUIDE.md,
        "Continuous-batching serving engine").

        `kv_dtype` (default: cfg.compute_dtype) picks the pool storage
        dtype. int8 (ISSUE 9) additionally allocates per-layer fp32
        scale pools `k/v_scales_layers` of (num_pages, page_size, g) —
        one symmetric scale per (token, group), written by the same
        scatter paths that write the data and consumed in-register by
        the ragged paged attention kernel (ops/prefill_attention.py,
        the one paged entry point) — roughly halving the pool's
        bytes/token
        (docs/GUIDE.md, "Quantized serving").

        `mesh_ctx` (ISSUE 14, the tp-sharded engine): a
        ParallelContext whose `model` axis the pools shard over —
        every pool leaf materialises DIRECTLY under its
        kv_pool_spec sharding (group axis over `model`,
        parallel/sharding.py — the per-chip pool is 1/tp the bytes,
        never allocated whole on one chip), while the page table and
        lengths stay replicated scalar-prefetch operands."""
        cfg = self.cfg
        kv_dtype = cfg.compute_dtype if kv_dtype is None else kv_dtype
        shape = (num_pages, page_size, cfg.num_query_groups, cfg.head_dim)

        if mesh_ctx is not None:
            import jax
            import numpy as np

            from megatron_llm_tpu.parallel.sharding import kv_pool_spec

            tp = mesh_ctx.tp

            def _sharded_zeros(shape, dtype, sh):
                # per-shard host zeros straight onto each device — no
                # whole-pool materialisation anywhere (the pool is the
                # largest allocation serving makes), and no jit (this
                # is a one-shot allocation, not a compile-contract
                # entry point)
                npdt = np.dtype(dtype)

                def cb(idx):
                    sub = [len(range(*s.indices(n)))
                           for s, n in zip(idx, shape)]
                    return np.zeros(sub, npdt)

                return jax.make_array_from_callback(shape, sh, cb)

            def zeros(shape, dtype):
                return _sharded_zeros(
                    shape, dtype,
                    mesh_ctx.sharding(*kv_pool_spec(shape, tp)))

            def zeros_rep(shape, dtype):
                return _sharded_zeros(shape, dtype, mesh_ctx.sharding())
        else:
            def zeros(shape, dtype):
                return jnp.zeros(shape, dtype)

            zeros_rep = zeros

        caches = {
            "k_pages_layers": tuple(zeros(shape, kv_dtype)
                                    for _ in range(cfg.num_layers)),
            "v_pages_layers": tuple(zeros(shape, kv_dtype)
                                    for _ in range(cfg.num_layers)),
            "page_table": zeros_rep((slots, max_pages_per_slot),
                                    jnp.int32),
            "lengths": zeros_rep((slots,), jnp.int32),
        }
        if jnp.dtype(kv_dtype) == jnp.int8:
            sshape = shape[:-1]
            caches["k_scales_layers"] = tuple(
                zeros(sshape, jnp.float32)
                for _ in range(cfg.num_layers))
            caches["v_scales_layers"] = tuple(
                zeros(sshape, jnp.float32)
                for _ in range(cfg.num_layers))
        return caches
