"""GQA/MQA self-attention, TPU-first.

Parity target: ref megatron/model/transformer.py:280-537 (`ParallelAttention`
+ `CoreAttention`). Differences by design:

- Layout is (batch, seq, ...) — the TPU-friendly convention — not the
  reference's (seq, batch, ...).
- GQA is computed *grouped*: Q is reshaped to (b, s, groups, q_per_kv, d)
  and contracted against un-expanded K/V of (b, t, groups, d). The
  reference instead broadcast-expands K/V to full head count
  (ref: transformer.py:449-456), which wastes HBM bandwidth; the einsum
  form lets the MXU consume the grouped operand directly.
- The fused-softmax CUDA kernels (ref: fused_kernels/scaled_*_softmax*) are
  unnecessary: the masked-softmax here is fused by XLA; the flash path is a
  Pallas kernel (ops/flash_attention.py).

The fused QKV weight keeps the reference's grouped layout
[group g: q_g(0..q_per_kv-1), k_g, v_g] along the output dim
(ref: transformer.py:316,449-456; weights2megatron.py:82-146) so converted
checkpoints drop in unchanged.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.models.remat import tag as _savepoint
from megatron_llm_tpu.models.rope import apply_rope
from megatron_llm_tpu.ops.quantization import qdot
from megatron_llm_tpu.parallel.mesh import (
    CONTEXT_AXIS,
    get_context,
    in_manual_region,
    shard_activation,
    shard_map as _shard_map,
)


def _ring_dispatch(pctx, q, k, v, doc_start=None):
    """Ring attention over the `context` mesh axis. Outside any manual
    region: a seq-sharded shard_map with `data`/`model` GSPMD-auto inside.
    Inside the pipeline's manual region `context` is already a manual axis
    of the enclosing shard_map (pipeline.py declares it when cp>1), so the
    ring body is called directly on the local seq shard. `doc_start`
    (b, s) — global document-start indices — rides along seq-sharded for
    packed-document (--reset_attention_mask) training."""
    import functools

    from jax.sharding import PartitionSpec as P

    from megatron_llm_tpu.parallel.ring_attention import ring_self_attention

    if in_manual_region():
        return ring_self_attention(q, k, v, CONTEXT_AXIS, causal=True,
                                   doc_start=doc_start)

    # the batch axis is manual too (the ring body is row-independent and
    # the activations are already data-sharded): with `data` inside the
    # manual set, pure dp x cp meshes reach this XLA build's fully-manual
    # path instead of its broken partial-manual partitioner
    # (parallel/mesh.py shard_map adapter) — and on newer builds it is
    # an equivalent, equally-correct manualization.
    from megatron_llm_tpu.parallel.mesh import DATA_AXIS

    qspec = P(DATA_AXIS, CONTEXT_AXIS, None, None, None)
    kspec = P(DATA_AXIS, CONTEXT_AXIS, None, None)
    if doc_start is None:
        ring = _shard_map(
            functools.partial(
                ring_self_attention, axis_name=CONTEXT_AXIS, causal=True
            ),
            in_specs=(qspec, kspec, kspec),
            out_specs=qspec,
            axis_names={DATA_AXIS, CONTEXT_AXIS},
            mesh=pctx.mesh,
        )
        return ring(q, k, v)

    ring = _shard_map(
        lambda q_, k_, v_, ds: ring_self_attention(
            q_, k_, v_, CONTEXT_AXIS, causal=True, doc_start=ds
        ),
        in_specs=(qspec, kspec, kspec, P(DATA_AXIS, CONTEXT_AXIS)),
        out_specs=qspec,
        axis_names={DATA_AXIS, CONTEXT_AXIS},
        mesh=pctx.mesh,
    )
    return ring(q, k, v, doc_start.astype(jnp.int32))


def _decode_kernel_block(cfg, s: int, t: int):
    """Static gate for the Pallas decode-attention kernel on the KV-cache
    paths: returns the cache block size, or None for the XLA fallback.
    Kernel territory is the single-token decode step (s == 1) against a
    cache of at least `decode_attn_min_cache` positions; prefill chunks
    (s > 1) keep the batched-GEMM path, which is compute-bound."""
    if not cfg.use_decode_attn:
        return None
    from megatron_llm_tpu.ops.decode_attention import decode_attn_block

    return decode_attn_block(
        s, cfg.q_per_kv, cfg.head_dim, t,
        min_cache=cfg.decode_attn_min_cache,
        interpret=cfg.decode_attn_interpret,
    )


def split_qkv(mixed: jnp.ndarray, cfg) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(b, s, qkv_size) -> q (b,s,g,qpk,d), k (b,s,g,d), v (b,s,g,d).

    Inverse of the reference's grouped view (ref: transformer.py:449-456).
    """
    b, s, _ = mixed.shape
    g, qpk, d = cfg.num_query_groups, cfg.q_per_kv, cfg.head_dim
    qkv = mixed.reshape(b, s, g, qpk + 2, d)
    q = qkv[:, :, :, :qpk]
    k = qkv[:, :, :, qpk]
    v = qkv[:, :, :, qpk + 1]
    return q, k, v


def grouped_attention(
    q: jnp.ndarray,  # (b, s, g, qpk, d)
    k: jnp.ndarray,  # (b, t, g, d)
    v: jnp.ndarray,  # (b, t, g, d)
    mask: Optional[jnp.ndarray],  # (b, 1, s, t) or (s, t); True = masked out
    cfg,
    dropout_rng=None,
    deterministic: bool = True,
) -> jnp.ndarray:
    """Reference (non-flash) attention path (ref: CoreAttention
    transformer.py:144-278) as one fused einsum chain, softmax in fp32
    (ref: attention_softmax_in_fp32 / fused-softmax kernels)."""
    b, s, g, qpk, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)

    # (b, g, qpk, s, t)
    scores = jnp.einsum(
        "bsgqd,btgd->bgqst", q, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    if mask is not None:
        if mask.ndim == 2:
            neg = jnp.finfo(scores.dtype).min
            scores = jnp.where(mask[None, None, None], neg, scores)
        else:  # (b, 1, s, t)
            neg = jnp.finfo(scores.dtype).min
            scores = jnp.where(mask[:, :, None], neg, scores)

    probs = jax.nn.softmax(scores, axis=-1)
    if not deterministic and cfg.attention_dropout > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(
            dropout_rng, 1.0 - cfg.attention_dropout, probs.shape
        )
        probs = probs * keep / (1.0 - cfg.attention_dropout)
    probs = probs.astype(v.dtype)

    ctx = jnp.einsum("bgqst,btgd->bsgqd", probs, v)
    return ctx.reshape(b, s, g * qpk * d)


def cross_attention_block(
    attn_params: dict,
    cfg,
    hidden: jnp.ndarray,  # (b, s, h) decoder side
    encoder_output: jnp.ndarray,  # (b, t, h)
    mask: Optional[jnp.ndarray],  # (b, 1, s, t) True = masked out
    dropout_rng=None,
    deterministic: bool = True,
) -> jnp.ndarray:
    """Encoder-decoder cross attention (ref: ParallelAttention with
    attention_type=cross_attn, transformer.py:331-354, 456-470): Q from the
    decoder hidden, fused KV from the encoder output, same grouped einsum
    core as self-attention."""
    b, s, h = hidden.shape
    dt = cfg.compute_dtype
    g, qpk, d = cfg.num_query_groups, cfg.q_per_kv, cfg.head_dim

    q = (hidden @ attn_params["wq"].astype(dt)).reshape(b, s, g, qpk, d)
    kv = encoder_output @ attn_params["wkv"].astype(dt)
    if "bq" in attn_params:
        q = q + attn_params["bq"].astype(dt).reshape(g, qpk, d)
    if "bkv" in attn_params:
        kv = kv + attn_params["bkv"].astype(dt)
    # same named save points as self-attention (models/remat.py): the q and
    # kv projections both carry the "qkv_proj" name
    q = _savepoint(q, "qkv_proj")
    kv = _savepoint(kv, "qkv_proj")
    t = encoder_output.shape[1]
    kv = kv.reshape(b, t, g, 2, d)
    k, v = kv[:, :, :, 0], kv[:, :, :, 1]
    q = shard_activation(q, "groups")
    ctx = grouped_attention(q, k, v, mask, cfg, dropout_rng, deterministic)
    ctx = _savepoint(ctx, "attn_ctx")
    out = ctx @ attn_params["wo"].astype(dt)
    if "bo" in attn_params:
        out = out + attn_params["bo"].astype(dt)
    return _savepoint(out, "attn_dense")


def padding_mask_2d(q_keep: jnp.ndarray,
                    k_keep: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Keep-masks (b, s_q) [x (b, s_k)] {0,1} -> (b, 1, s_q, s_k)
    True-=-masked, the outer-product form (ref:
    bert_extended_attention_mask bert_model.py:21-35 and the enc-dec
    cross mask, t5_dataset.py make_attention_mask)."""
    if k_keep is None:
        k_keep = q_keep
    keep = q_keep.astype(jnp.float32)[:, :, None] * \
        k_keep.astype(jnp.float32)[:, None, :]
    return (keep < 0.5)[:, None]


def causal_mask(s: int, t: Optional[int] = None, offset: int = 0) -> jnp.ndarray:
    """(s, t) boolean mask, True = masked (ref convention:
    utils.py:137-196 builds mask with `< 0.5` => masked True)."""
    t = t if t is not None else s
    rows = jnp.arange(s)[:, None] + offset
    cols = jnp.arange(t)[None, :]
    return cols > rows


def attention_block(
    attn_params: dict,
    cfg,
    hidden: jnp.ndarray,  # (b, s, h)
    rope_table: Optional[jnp.ndarray],
    mask: Optional[jnp.ndarray],
    position_ids: Optional[jnp.ndarray],
    dropout_rng=None,
    deterministic: bool = True,
    kv_cache: Optional[dict] = None,
) -> Tuple[jnp.ndarray, Optional[dict]]:
    """Full attention sublayer: fused qkv proj -> RoPE -> (cached) attention
    -> output proj (ref: ParallelAttention.forward transformer.py:412-537).

    `kv_cache` for incremental decode (ref: InferenceParams
    forward_step.py:17, transformer.py:483-496), three forms:
    - stacked (the decode hot path, what transformer_stack passes):
      {"k": (L, b, maxT, g, d), "v": ..., "offset": scalar, "layer": idx}
      — this layer's token column is updated IN PLACE inside the stack;
    - per-layer {"k": (b, maxT, g, d), "v": ..., "offset": scalar} for
      standalone single-layer use;
    - paged (the continuous-batching engine, inference/engine.py):
      {"k_pages": (P, page_size, g, d), "v_pages": ..., "page_table":
      (slots, max_pages) int32, "lengths": (slots,) int32, optionally
      "chunk_lens": (slots,) int32} — the batch axis is SLOTS at ragged
      per-slot lengths; slot i contributes a ragged span of
      chunk_lens[i] tokens starting at cache position lengths[i] (s is
      the padded chunk width; 1 == a decode row, 0 == idle), scattered
      into the slot's pages + attended in one ragged pass by THE paged
      kernel (ops/prefill_attention.ragged_paged_attention — ISSUE 18:
      decode scans, mixed rounds, and spec-verify all land here).
      Without "chunk_lens" the form is the engine's single-token decode
      step (s == 1): every slot is a width-1 chunk at its length.

    On a tp serving mesh (DecodeEngine(serving_tp>1), ISSUE 14) BOTH
    paged forms run group-sharded with no changes here: the pools
    arrive sharded on the group axis (kv_pool_spec), the existing
    shard_activation("groups"/"heads") constraint sites steer q and
    the attention output onto the same split, and GSPMD partitions the
    scatter + attention per shard (each chip runs the kernels — or
    their XLA twins — over its own groups against replicated page
    tables/lengths). The wo matmul below is the step's one collective
    (row-parallel partial-sum all-reduce, pinned by the tp2 audit
    rows).
    """
    b, s, h = hidden.shape
    compute_dtype = cfg.compute_dtype

    # qdot: `hidden @ wqkv.astype(dt)` for fp weights (bitwise the old
    # call), int8 GEMV + per-channel scale for weight-only quantized
    # decode trees (prepare_decode_params(quantize_int8=True))
    mixed = qdot(hidden, attn_params["wqkv"], compute_dtype)
    if "bqkv" in attn_params:
        mixed = mixed + attn_params["bqkv"].astype(compute_dtype)
    # named save point: under remat_policy selective/offload the fused QKV
    # projection is kept for backward; q/k/v (incl. RoPE) rebuild from it
    # with elementwise ops only (models/remat.py)
    mixed = _savepoint(mixed, "qkv_proj")
    q, k, v = split_qkv(mixed, cfg)
    q = shard_activation(q, "groups")

    if kv_cache is not None and "k_pages" in kv_cache:
        # THE paged branch (ISSUE 18 — the engine's one attention path):
        # slot i contributes a contiguous span of chunk_lens[i] tokens
        # (<= s, ragged; 0 = idle) starting at cache position
        # lengths[i]. The span's K/V is scattered into the slot's pages
        # and attention runs causally against everything the slot has
        # cached INCLUDING the span itself, in one pass
        # (ops/prefill_attention.ragged_paged_attention). Phase is a
        # shape: the engine's decode scan passes no "chunk_lens" — every
        # slot is then a width-1 chunk at its length, the exact decode
        # semantics (attend positions 0..lengths[i] inclusive of the
        # just-written token; retired slots carry all-null page-table
        # rows, so their writes land on the pool's dead null page 0).
        g, qpk, d = cfg.num_query_groups, cfg.q_per_kv, cfg.head_dim
        lengths = kv_cache["lengths"]
        chunked = "chunk_lens" in kv_cache
        if chunked:
            chunk_lens = kv_cache["chunk_lens"]
        else:
            assert s == 1, \
                "paged KV cache without chunk_lens serves single-token " \
                "decode steps"
            chunk_lens = jnp.ones_like(lengths)
        page_table = kv_cache["page_table"]
        if position_ids is None:
            position_ids = lengths[:, None] + jnp.arange(s)[None, :]
        if rope_table is not None:
            q = apply_rope(q, rope_table, position_ids)
            k = apply_rope(k, rope_table, position_ids)
        from megatron_llm_tpu.ops.prefill_attention import (
            ragged_paged_attention,
        )

        # one gate, inside the entry point (ragged_paged_block):
        # use_pallas=True means "kernel if eligible, XLA twin
        # otherwise"; ONE gate means a decode row takes the SAME
        # kernel-vs-XLA path in scan and mixed steps by construction
        quantized = "k_scales" in kv_cache  # int8 pools (ISSUE 9)
        # sliding window (ISSUE 19) rides the model config — static,
        # so every serving trace of a window-enabled model bakes the
        # O(window) clamp in; None leaves the trace byte-identical.
        # "doc_starts" (packed multi-doc prefill floors) is a cache
        # key like "chunk_lens": present only when the caller packs
        # documents, absent from the engine's carries.
        doc_starts = kv_cache.get("doc_starts")
        res = ragged_paged_attention(
            q, k, v, kv_cache["k_pages"], kv_cache["v_pages"],
            page_table, lengths, chunk_lens,
            use_pallas=cfg.use_decode_attn,
            min_cache=cfg.decode_attn_min_cache,
            interpret=cfg.decode_attn_interpret,
            k_scales=kv_cache.get("k_scales"),
            v_scales=kv_cache.get("v_scales"),
            window_size=getattr(cfg, "attention_window_size", None),
            doc_starts=doc_starts,
        )
        # cache pytree layout is carry-stable: "chunk_lens" stays a key
        # only in the chunked form (the decode scan's carry never grows)
        new_cache = {"page_table": page_table,
                     "lengths": lengths + chunk_lens}
        if chunked:
            new_cache["chunk_lens"] = chunk_lens
        if doc_starts is not None:
            new_cache["doc_starts"] = doc_starts
        if quantized:
            (ctx, new_cache["k_pages"], new_cache["v_pages"],
             new_cache["k_scales"], new_cache["v_scales"]) = res
        else:
            ctx, new_cache["k_pages"], new_cache["v_pages"] = res
        ctx = shard_activation(ctx.reshape(b, s, g, qpk * d), "heads") \
            .reshape(b, s, -1)
        out = qdot(ctx, attn_params["wo"], compute_dtype)
        if "bo" in attn_params:
            out = out + attn_params["bo"].astype(compute_dtype)
        return out, new_cache
    if kv_cache is not None:
        offset = kv_cache["offset"]
        if position_ids is None:
            position_ids = offset + jnp.arange(s)[None, :]
        if rope_table is not None:
            q = apply_rope(q, rope_table, position_ids)
            k = apply_rope(k, rope_table, position_ids)
        if "k_gtd" in kv_cache:
            # decode fast path: per-layer standalone (b, g, T, d) caches
            # (init_kv_caches layout="layers") — column updates and
            # attention reads hit a small contiguous buffer in place, no
            # per-layer stack slicing. (A (b, g, d, T) K layout was also
            # measured: the minor-axis column scatter cost more than the
            # sublane-reduce saved.)
            g, qpk, d = cfg.num_query_groups, cfg.q_per_kv, cfg.head_dim
            kc = jax.lax.dynamic_update_slice(
                kv_cache["k_gtd"], k.transpose(0, 2, 1, 3), (0, 0, offset, 0)
            )
            vc = jax.lax.dynamic_update_slice(
                kv_cache["v_gtd"], v.transpose(0, 2, 1, 3), (0, 0, offset, 0)
            )
            new_cache = {"k_gtd": kc, "v_gtd": vc, "offset": offset + s}
            t = kc.shape[2]
            bt = _decode_kernel_block(cfg, s, t)
            if bt is not None:
                # Pallas decode-attention kernel: streams the cache at
                # line rate with in-kernel length masking (the XLA
                # matvecs run far under HBM bandwidth at s == 1)
                from megatron_llm_tpu.ops.decode_attention import (
                    decode_attention,
                )

                ctx = decode_attention(
                    q, kc, vc, offset + s, layout="gtd", use_pallas=True,
                    block_t=bt, interpret=cfg.decode_attn_interpret,
                )
            else:
                from megatron_llm_tpu.ops.decode_attention import (
                    _xla_decode,
                )

                # the kernel's shapes-and-math twin (batched GEMMs +
                # O(s*t) iota mask) — ONE definition so the exact-match
                # tests pin the kernel against the code that actually
                # serves the fallback
                ctx = _xla_decode(q, kc, vc, offset + s, "gtd")
            ctx = shard_activation(ctx.reshape(b, s, g, qpk * d), "heads") \
                .reshape(b, s, -1)
            out = qdot(ctx, attn_params["wo"], compute_dtype)
            if "bo" in attn_params:
                out = out + attn_params["bo"].astype(compute_dtype)
            return out, new_cache
        if "layer" in kv_cache:
            # stacked-cache form (decode hot path): update THIS layer's
            # token column in place inside the full (L, b, T, g, d) stack
            # and slice the layer back for attention. Updating only the
            # written column (instead of materializing a new per-layer
            # buffer and re-stacking it through scan ys) measured 2.2x
            # faster per decode step at b=8/T=576 on v5e.
            lidx = kv_cache["layer"]
            kc = jax.lax.dynamic_update_slice(
                kv_cache["k"], k[None], (lidx, 0, offset, 0, 0)
            )
            vc = jax.lax.dynamic_update_slice(
                kv_cache["v"], v[None], (lidx, 0, offset, 0, 0)
            )
            k_full = jax.lax.dynamic_index_in_dim(kc, lidx, 0, False)
            v_full = jax.lax.dynamic_index_in_dim(vc, lidx, 0, False)
            new_cache = {"k": kc, "v": vc, "offset": offset + s,
                         "layer": lidx}
        else:
            k_full = jax.lax.dynamic_update_slice_in_dim(
                kv_cache["k"], k, offset, axis=1)
            v_full = jax.lax.dynamic_update_slice_in_dim(
                kv_cache["v"], v, offset, axis=1)
            new_cache = {"k": k_full, "v": v_full, "offset": offset + s}
        t = k_full.shape[1]
        bt = _decode_kernel_block(cfg, s, t)
        if bt is not None:
            # stage-ring pipelined decode ticks land here (stacked cache,
            # s == 1): stream this layer's (b, T, g, d) cache slice
            # through the decode kernel in place — no transpose, no dense
            # (s, t) mask
            from megatron_llm_tpu.ops.decode_attention import (
                decode_attention,
            )

            ctx = decode_attention(
                q, k_full, v_full, offset + s, layout="tgd",
                use_pallas=True, block_t=bt,
                interpret=cfg.decode_attn_interpret,
            ).reshape(b, s, -1)
        else:
            # rows attend to cols <= offset+row
            rows = offset + jnp.arange(s)[:, None]
            cols = jnp.arange(t)[None, :]
            dec_mask = cols > rows  # (s, t)
            ctx = grouped_attention(q, k_full, v_full, dec_mask, cfg,
                                    dropout_rng, deterministic=True)
    else:
        if rope_table is not None:
            q = apply_rope(q, rope_table, position_ids)
            k = apply_rope(k, rope_table, position_ids)
        # Packed-document masking (--reset_attention_mask) arrives as
        # {"doc_start": (b, s)} — O(s) instead of a dense (s, s) mask —
        # and stays SEQ-SHARDED through the ring (VERDICT r4 #5).
        doc_start = None
        if isinstance(mask, dict):
            doc_start = mask["doc_start"]
            mask = None
        # flash path has no dropout support: fall back to the grouped path
        # when attention dropout is live (ADVICE r1; the reference's
        # FlashSelfAttention passes dropout to the CUDA kernel instead)
        no_dropout = deterministic or cfg.attention_dropout == 0.0
        pctx = get_context()
        # Context parallelism: when the mesh has a context axis, attention
        # is the ONE op that mixes sequence positions — run the exact ring
        # (scan + ppermute, parallel/ring_attention.py) over seq shards.
        # RoPE was applied above with global position_ids, so q/k enter the
        # ring already rotated.
        ring_ok = (
            pctx is not None and pctx.cp > 1 and mask is None and no_dropout
        )
        if pctx is not None and pctx.cp > 1 and mask is not None:
            # LOUD refusal (was a silent gathered-attention fallback):
            # a dense mask under cp would force a full-sequence gather,
            # quietly losing the memory scaling cp exists for. The CLI
            # path never gets here — args_to_configs rejects BERT/T5
            # (padding-mask models, which have no doc_start form) at
            # config construction; this guard catches direct library
            # callers.
            raise ValueError(
                "cp>1 with a dense attention mask: pass packed-document "
                "masks as {'doc_start': (b, s)} (utils/masks.py "
                "get_document_starts) to keep the sequence sharded, or "
                "disable context parallelism for this model. "
                "BERT/T5-style PADDING masks have no doc_start "
                "equivalent — those model families must run with cp=1 "
                "(rejected at config construction on the CLI path; "
                "docs/GUIDE.md 'Masks')"
            )
        if (pctx is not None and pctx.cp > 1 and doc_start is not None
                and not no_dropout):
            # same loudness for the dropout corner: the ring has no
            # attention-dropout path, and falling back to gathered
            # attention would silently lose cp's memory scaling
            raise ValueError(
                "cp>1 packed-document attention requires "
                "attention_dropout == 0 (ring attention has no dropout "
                "path)"
            )
        if doc_start is not None and not ring_ok:
            # single-device / no-cp path: expand to the dense equivalent
            rows = jnp.arange(s)[None, :, None]
            cols = jnp.arange(s)[None, None, :]
            mask = ((cols > rows) |
                    (cols < doc_start[:, :, None]))[:, None]
        flash_ok = cfg.use_flash_attn and mask is None and no_dropout \
            and doc_start is None
        if ring_ok:
            ctx = _ring_dispatch(pctx, q, k, v, doc_start=doc_start)
            ctx = _savepoint(ctx, "attn_ctx").reshape(b, s, -1)
        elif flash_ok:
            from megatron_llm_tpu.ops.flash_attention import flash_attention

            # flash output + logsumexp are tagged INSIDE the wrapper
            # ("attn_ctx"/"flash_lse", ops/flash_attention.py) so the
            # selective policy can keep both and the backward never
            # re-runs the forward kernel
            ctx = flash_attention(q, k, v, causal=True)
            ctx = ctx.reshape(b, s, -1)
        else:
            if mask is None:
                mask = causal_mask(s)
            # The O(s*t) softmax probabilities are NOT a named save point:
            # under any remat policy but "none" they are recomputed from
            # the saved "qkv_proj" (the reference's selective-granularity
            # behavior, ref: transformer.py:357-401, now expressed by the
            # name policy in models/remat.py rather than a nested
            # jax.checkpoint around the core).
            ctx = grouped_attention(q, k, v, mask, cfg, dropout_rng,
                                    deterministic)
            ctx = _savepoint(ctx, "attn_ctx")
        new_cache = None

    ctx = shard_activation(
        ctx.reshape(b, s, cfg.num_query_groups, cfg.q_per_kv * cfg.head_dim),
        "heads",
    ).reshape(b, s, -1)
    out = qdot(ctx, attn_params["wo"], compute_dtype)
    if "bo" in attn_params:
        out = out + attn_params["bo"].astype(compute_dtype)
    out = _savepoint(out, "attn_dense")
    return out, new_cache
