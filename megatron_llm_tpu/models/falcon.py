"""Falcon 7B/40B (ref: megatron/model/falcon_model.py:10-42)."""

from __future__ import annotations

from megatron_llm_tpu.models.gpt import GPTModel


class FalconModel(GPTModel):
    """Asserts the Falcon architectural invariants the reference enforces
    (ref: falcon_model.py:18-29): rotary + MQA/GQA + parallel attention;
    parallel_layernorm distinguishes 40B from 7B."""

    def _check_config(self):
        cfg = self.cfg
        assert cfg.position_embedding_type == "rotary", "falcon requires RoPE"
        assert cfg.parallel_attn, "falcon uses parallel attention"
        assert cfg.num_attention_heads_kv < cfg.num_attention_heads, (
            "falcon uses MQA/GQA"
        )
        assert not cfg.use_post_ln
