"""GLU activation zoo (ref: megatron/model/glu_activations.py:24-55).

Each GLU splits the doubled up-projection in half along the last dim and
gates: act(x1) * x2. The registry mirrors the reference's
`GLU_ACTIVATIONS` dict (ref: glu_activations.py:50-55).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _split(x: jnp.ndarray):
    return jnp.split(x, 2, axis=-1)


def liglu(x):
    a, b = _split(x)
    return a * b


def geglu(x):
    a, b = _split(x)
    return jax.nn.gelu(a, approximate=False) * b


def reglu(x):
    a, b = _split(x)
    return jax.nn.relu(a) * b


def swiglu(x):
    a, b = _split(x)
    return jax.nn.silu(a) * b


GLU_ACTIVATIONS = {
    "liglu": liglu,
    "geglu": geglu,
    "reglu": reglu,
    "swiglu": swiglu,
}

ACTIVATIONS = {
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
}


def mlp_activation(cfg):
    """Resolve the MLP activation from config (GLU takes precedence)."""
    if cfg.glu_activation is not None:
        return GLU_ACTIVATIONS[cfg.glu_activation]
    return ACTIVATIONS[cfg.hidden_act]
