"""GLU activation zoo (ref: megatron/model/glu_activations.py:24-55).

Each GLU gates an up-projection: act(gate) * up. The packed-tensor helpers
(`*_packed`) split the last dim in half like the reference; the two-argument
forms are used by the MLP, whose weights keep gate/up on a dedicated axis
(see models/transformer.py) so TP sharding never crosses the boundary.
The registry mirrors the reference's `GLU_ACTIVATIONS` dict
(ref: glu_activations.py:50-55).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jax.ad_checkpoint import checkpoint_name


def _named(x):
    """Tag the activation output as the "mlp_act" save point (identity at
    runtime). Deliberately NOT in the selective save set (models/remat.py):
    it recomputes elementwise from the saved "mlp_pre_act" projection, and
    at GLU widths it is the largest tensor the policy gets to drop — the
    name exists so future policies (and print_saved_residuals audits) can
    address it."""
    return checkpoint_name(x, "mlp_act")


def liglu(gate, up):
    return _named(gate * up)


def geglu(gate, up):
    return _named(jax.nn.gelu(gate, approximate=False) * up)


def reglu(gate, up):
    return _named(jax.nn.relu(gate) * up)


def swiglu(gate, up):
    return _named(jax.nn.silu(gate) * up)


GLU_ACTIVATIONS = {
    "liglu": liglu,
    "geglu": geglu,
    "reglu": reglu,
    "swiglu": swiglu,
}

ACTIVATIONS = {
    "gelu": lambda x: _named(jax.nn.gelu(x, approximate=False)),
    "gelu_tanh": lambda x: _named(jax.nn.gelu(x, approximate=True)),
    "relu": lambda x: _named(jax.nn.relu(x)),
    "silu": lambda x: _named(jax.nn.silu(x)),
}


def _packed(fn):
    def apply(x):
        gate, up = jnp.split(x, 2, axis=-1)
        return fn(gate, up)

    return apply


# Reference-layout variants taking one packed [gate; up] tensor
# (ref: glu_activations.py:24-47 chunk(2, dim=-1)); used by the checkpoint
# converters and activation parity tests.
GLU_ACTIVATIONS_PACKED = {name: _packed(fn) for name, fn in GLU_ACTIVATIONS.items()}
