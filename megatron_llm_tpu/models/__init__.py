from megatron_llm_tpu.models.gpt import GPTModel  # noqa: F401
from megatron_llm_tpu.models.llama import LlamaModel  # noqa: F401
from megatron_llm_tpu.models.falcon import FalconModel  # noqa: F401
from megatron_llm_tpu.models.bert import BertModel  # noqa: F401
from megatron_llm_tpu.models.t5 import T5Model  # noqa: F401
