"""Rotary position embeddings, Meta-Llama interleaved-pair convention.

Behavioral parity with ref: megatron/model/positional_embeddings.py:7-52 —
freqs 1/theta^(2i/d), positions divided by `scaling_factor` (position
interpolation), and rotation applied to *adjacent* element pairs
(x[2i], x[2i+1]) via complex multiplication. We carry (cos, sin) tables
instead of complex64 (XLA on TPU prefers real arithmetic), computed in fp32.
"""

from __future__ import annotations

import jax.numpy as jnp


def precompute_rope(
    head_dim: int,
    max_len: int,
    theta: float = 10000.0,
    scaling_factor: float = 1.0,
) -> jnp.ndarray:
    """Return (max_len, head_dim//2, 2) fp32 table of (cos, sin).

    Equivalent to the reference's complex `freqs_cis` table
    (ref: positional_embeddings.py:7-14).
    """
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_len, dtype=jnp.float32) / scaling_factor
    freqs = jnp.outer(t, inv_freq)  # (max_len, head_dim//2)
    return jnp.stack([jnp.cos(freqs), jnp.sin(freqs)], axis=-1)


def apply_rope(
    x: jnp.ndarray,
    rope: jnp.ndarray,
    position_ids: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Rotate `x` of shape (batch, seq, *head_dims, head_dim) — seq at axis 1.

    Matches the reference's complex multiply on interleaved pairs
    (ref: positional_embeddings.py:24-52): for each adjacent pair
    (xr, xi): (xr*cos - xi*sin, xr*sin + xi*cos).

    `rope` is the table from `precompute_rope`; `position_ids` (batch, seq)
    selects rows, defaulting to arange(seq) (ref: positional_embeddings.py:36-47).
    """
    seq = x.shape[1]
    n_mid = x.ndim - 3  # head-like dims between seq and head_dim
    if position_ids is None:
        cs = rope[:seq][None]  # (1, seq, d/2, 2)
    else:
        cs = rope[position_ids]  # (batch, seq, d/2, 2)
    # -> (batch, seq, *(1,)*n_mid, d/2, 2)
    cs = cs.reshape(cs.shape[0], seq, *((1,) * n_mid), -1, 2)
    cos, sin = cs[..., 0], cs[..., 1]

    xf = x.astype(jnp.float32).reshape(*x.shape[:-1], -1, 2)
    xr, xi = xf[..., 0], xf[..., 1]
    out_r = xr * cos - xi * sin
    out_i = xr * sin + xi * cos
    out = jnp.stack([out_r, out_i], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)
