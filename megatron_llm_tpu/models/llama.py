"""Llama 1/2 + CodeLlama (ref: megatron/model/llama_model.py:10-44)."""

from __future__ import annotations

from megatron_llm_tpu.models.gpt import GPTModel


class LlamaModel(GPTModel):
    """Asserts the Llama architectural invariants the reference enforces
    (ref: llama_model.py:22-30)."""

    def _check_config(self):
        cfg = self.cfg
        assert cfg.position_embedding_type == "rotary", "llama requires RoPE"
        assert cfg.glu_activation == "swiglu", "llama requires SwiGLU"
        assert cfg.use_rms_norm, "llama requires RMSNorm"
        assert not cfg.use_bias, "llama uses no bias"
        assert not cfg.use_post_ln, "llama is pre-LN"
        assert not cfg.tie_embed_logits, "llama has untied embeddings"
        assert not cfg.parallel_attn
