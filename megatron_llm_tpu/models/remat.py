"""Named-savepoint activation-recompute policies.

The reference trades memory for FLOPs with --recompute_granularity
(ref: arguments.py:606-630, random.py:175-247): "full" re-runs every layer
in backward, "selective" keeps everything EXCEPT the O(s^2) attention core.
Here the same ladder — and two rungs the reference doesn't have — is built
from jax.checkpoint policies over NAMED save points: the expensive matmul
outputs are tagged with `jax.ad_checkpoint.checkpoint_name` at their
definition sites, and each policy decides which names survive to backward.

Save-point names (tagged once per runtime path):

| name          | tensor                          | tagged in             |
|---------------|---------------------------------|-----------------------|
| `qkv_proj`    | fused QKV projection output     | models/attention.py   |
| `attn_ctx`    | attention context (flash out /  | ops/flash_attention.py|
|               | ring / grouped einsum output)   | + models/attention.py |
| `flash_lse`   | flash kernel row logsumexp      | ops/flash_attention.py|
|               | (custom-VJP residual; saving it |                       |
|               | + attn_ctx means backward never |                       |
|               | re-runs the forward kernel)     |                       |
| `attn_dense`  | attention output projection     | models/attention.py   |
| `mlp_pre_act` | pre-GLU/act MLP up-projection   | models/transformer.py |
| `mlp_act`     | activation/GLU-combine output   | models/activations.py |
| `mlp_out`     | MLP down-projection output      | models/transformer.py |

Policies (ModelConfig.remat_policy / ParallelConfig.pipeline_remat):

- "full":      checkpoint with no policy — save only what crosses the
               checkpoint boundary, recompute everything (+~1/3 FLOPs).
- "selective": save_only_these_names(SELECTIVE_SAVE_NAMES) — every matmul
               output above EXCEPT `mlp_act` (elementwise, cheap to
               recompute from the saved `mlp_pre_act`); backward recomputes
               only elementwise ops (norms, GLU, rope, residual adds) and
               the attention core stays free via the flash custom VJP.
- "save_dots": jax.checkpoint_policies.checkpoint_dots — keep every dot
               output, named or not (FLOP floor, more live HBM).
- "offload":   the selective save set, parked in PINNED HOST memory
               (save_and_offload_only_these_names) — device HBM like
               "full", FLOPs like "selective", paid in host-DMA traffic;
               the long-sequence lever.
- "none":      no checkpoint wrapper at all.
"""

from __future__ import annotations

import jax
from jax.ad_checkpoint import checkpoint_name

from megatron_llm_tpu.config import REMAT_POLICIES

# every tagged save point (see the table above)
CHECKPOINT_NAMES = (
    "qkv_proj",
    "attn_ctx",
    "flash_lse",
    "attn_dense",
    "mlp_pre_act",
    "mlp_act",
    "mlp_out",
)

# what "selective" keeps: the matmul outputs (+ the tiny flash logsumexp
# rows so backward never re-runs the forward flash kernel). `mlp_act` is
# deliberately absent — it is elementwise-recomputable from `mlp_pre_act`
# for free, and at GLU widths it is the single largest remaining tensor.
SELECTIVE_SAVE_NAMES = (
    "qkv_proj",
    "attn_ctx",
    "flash_lse",
    "attn_dense",
    "mlp_pre_act",
    "mlp_out",
)

# the offload policy ships the same set to pinned host memory
OFFLOAD_NAMES = SELECTIVE_SAVE_NAMES


def tag(x, name: str):
    """Tag a tensor as a named save point (identity at runtime)."""
    assert name in CHECKPOINT_NAMES, name
    return checkpoint_name(x, name)


def remat_policy_fn(policy: str):
    """Policy name -> the jax.checkpoint `policy=` callable (None for the
    "full" no-policy checkpoint). Callers must special-case "none" (no
    checkpoint wrapper); `remat_wrap` below does."""
    cp = jax.checkpoint_policies
    if policy == "full":
        return None
    if policy == "selective":
        return cp.save_only_these_names(*SELECTIVE_SAVE_NAMES)
    if policy == "save_dots":
        return cp.checkpoint_dots
    if policy == "offload":
        return cp.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=list(OFFLOAD_NAMES),
            offload_src="device",
            offload_dst="pinned_host",
        )
    raise ValueError(
        f"remat policy {policy!r}: expected one of {REMAT_POLICIES}"
    )


def remat_wrap(fn, policy: str, prevent_cse: bool = False):
    """Apply the named remat policy to `fn` (a scan body / pipeline tick).
    "none" returns `fn` untouched; everything else is jax.checkpoint with
    the matching saveable policy. prevent_cse=False is safe under scan
    (the standard remat-in-scan setting used throughout this repo)."""
    if policy == "none":
        return fn
    return jax.checkpoint(
        fn, prevent_cse=prevent_cse, policy=remat_policy_fn(policy)
    )
