"""Decoder transformer stack — scan-over-layers, remat-aware.

Parity target: ref megatron/model/transformer.py (`ParallelMLP` :77,
`ParallelTransformerLayer` :582, `ParallelTransformer` :897). TPU-first
departures:

- Layer weights are *stacked* along a leading layer axis and the stack is a
  single `jax.lax.scan`, so the whole model compiles once regardless of
  depth (the reference's Python per-layer loop, transformer.py:1236-1242,
  is a CUDA-graph idiom XLA doesn't need).
- Activation recompute is `jax.checkpoint` on the scanned body, driven by
  the named-savepoint policy ladder (models/remat.py;
  ModelConfig.remat_policy full/selective/save_dots/offload/none —
  ref: recompute_granularity arguments.py:606-630, random.py:175-247).
- Residual structure covers pre/post-LN, Falcon parallel-attention and
  parallel-layernorm variants (ref: transformer.py:613-634, 774-806).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.models.activations import ACTIVATIONS, GLU_ACTIVATIONS
from megatron_llm_tpu.models.attention import attention_block
from megatron_llm_tpu.models.norms import apply_norm
from megatron_llm_tpu.models.remat import remat_wrap, tag as _savepoint
from megatron_llm_tpu.ops.quantization import is_quantized_weight, qdot
from megatron_llm_tpu.parallel.mesh import shard_activation


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def _normal(key, shape, std, dtype):
    return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


def init_norm_params(cfg, shape_prefix=()) -> dict:
    p = {"scale": jnp.ones(shape_prefix + (cfg.hidden_size,), cfg.params_dtype)}
    if not cfg.use_rms_norm:
        p["bias"] = jnp.zeros(shape_prefix + (cfg.hidden_size,), cfg.params_dtype)
    return p


def init_layer_params(cfg, key, num_layers: Optional[int] = None) -> dict:
    """Stacked per-layer weights, leading axis = layer.

    Init distributions follow the reference (ref: model/utils.py:11-24,
    layers.py:79-125): normal(0, std) for inputs projections, and
    normal(0, std/sqrt(2*num_layers)) for the residual-output projections
    (wo, w2) when use_scaled_init_method.
    """
    L = num_layers if num_layers is not None else cfg.num_layers
    h = cfg.hidden_size
    std = cfg.init_method_std
    out_std = std / jnp.sqrt(2.0 * cfg.num_layers) if cfg.use_scaled_init_method else std
    keys = jax.random.split(key, 4)
    dt = cfg.params_dtype

    attn = {
        "wqkv": _normal(keys[0], (L, h, cfg.qkv_projection_size), std, dt),
        "wo": _normal(
            keys[1],
            (L, cfg.num_attention_heads * cfg.head_dim, h),
            out_std,
            dt,
        ),
    }
    # GLU up-projections are stored (L, h, 2, ffn) — the gate/up axis kept
    # separate from the ffn axis — so TP sharding of ffn over the model axis
    # never crosses the gate/up boundary (the reference packs them into one
    # 2*ffn dim, ref: transformer.py:92-102, which forces an interleaved
    # per-rank layout; checkpoint converters reshape (h, 2*ffn) <-> (h, 2, ffn)).
    if cfg.glu_activation:
        w1_shape = (L, h, 2, cfg.ffn_hidden_size)
        b1_shape = (L, 2, cfg.ffn_hidden_size)
    else:
        w1_shape = (L, h, cfg.ffn_hidden_size)
        b1_shape = (L, cfg.ffn_hidden_size)
    mlp = {
        "w1": _normal(keys[2], w1_shape, std, dt),
        "w2": _normal(keys[3], (L, cfg.ffn_hidden_size, h), out_std, dt),
    }
    if cfg.use_bias:
        attn["bqkv"] = jnp.zeros((L, cfg.qkv_projection_size), dt)
        attn["bo"] = jnp.zeros((L, h), dt)
        mlp["b1"] = jnp.zeros(b1_shape, dt)
        mlp["b2"] = jnp.zeros((L, h), dt)

    layers = {
        "input_norm": init_norm_params(cfg, (L,)),
        "attention": attn,
        "mlp": mlp,
    }
    # post-attention norm exists unless Falcon-style parallel_attn without
    # a dedicated mlp norm (ref: transformer.py:613-634).
    if not cfg.parallel_attn:
        layers["post_attention_norm"] = init_norm_params(cfg, (L,))
    if cfg.parallel_layernorm:
        layers["mlp_norm"] = init_norm_params(cfg, (L,))
    return layers


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def mlp_block(mlp_params, cfg, hidden, dropout_rng, deterministic):
    """ParallelMLP (ref: transformer.py:77-142): h -> [2x]ffn -> act -> h.

    Weight-only int8 decode trees (prepare_decode_params
    (quantize_int8=True), ISSUE 9) arrive with w1/w2 as
    {"int8_data", "scale"} dicts — always in the pre-flattened 2D
    decode layout — and route through `qdot` (int8 GEMV + per-channel
    scale); fp weights take the bitwise-unchanged matmuls."""
    dt = cfg.compute_dtype
    w1 = mlp_params["w1"]
    if cfg.glu_activation:
        if is_quantized_weight(w1) or w1.ndim == 2:
            # Pre-flattened (h, 2f) decode layout (see
            # prepare_decode_params): the (h, 2, f) einsum tiles the
            # 2-sized gate/up axis into sublanes and streams the weight
            # at ~33% of HBM bandwidth at single-token shapes (traced on
            # v5e); the SAME bytes as one flat matvec stream at ~72%
            # like every other GEMV.
            b, s, h = hidden.shape
            x = qdot(hidden, w1, dt).reshape(b, s, 2, -1)
        else:
            # (b,s,h) @ (h,2,f) -> (b,s,2,f); gate/up on their own axis.
            # Also the tp-sharded DECODE path (ISSUE 14): mesh engines
            # keep this layout (prepare_decode_params(flatten_glu=
            # False)) so f shards over `model` and the GLU combine
            # stays elementwise-local per chip — the flat (h, 2f) view
            # concatenates gate|up along exactly the sharded axis.
            x = jnp.einsum("bsh,hcf->bscf", hidden, w1.astype(dt))
        if "b1" in mlp_params:
            x = x + mlp_params["b1"].astype(dt)
        # named save point: the pre-GLU up-projection — what the selective
        # policy keeps so the gate/up GEMM never re-runs in backward (the
        # GLU combine itself is the unnamed-elementwise part it recomputes)
        x = _savepoint(x, "mlp_pre_act")
        x = shard_activation(x, "glu_ffn")
        act = GLU_ACTIVATIONS[cfg.glu_activation]
        x = act(x[..., 0, :], x[..., 1, :])
    else:
        x = qdot(hidden, w1, dt)
        if "b1" in mlp_params:
            x = x + mlp_params["b1"].astype(dt)
        x = _savepoint(x, "mlp_pre_act")
        x = ACTIVATIONS[cfg.hidden_act](x)
    x = shard_activation(x, "ffn")
    x = qdot(x, mlp_params["w2"], dt)
    if "b2" in mlp_params:
        x = x + mlp_params["b2"].astype(dt)
    return _savepoint(x, "mlp_out")


def _dropout(x, rate, rng, deterministic):
    if deterministic or rate == 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return x * keep / (1.0 - rate)


def transformer_layer(
    layer_params: dict,
    cfg,
    hidden: jnp.ndarray,
    rope_table,
    mask,
    position_ids,
    dropout_rng=None,
    deterministic: bool = True,
    kv_cache: Optional[dict] = None,
    hidden_dropout_rate: Optional[float] = None,
) -> Tuple[jnp.ndarray, Optional[dict]]:
    """One decoder layer (ref: ParallelTransformerLayer.forward
    transformer.py:695-817), covering:

    - pre-LN (default) / post-LN (cfg.use_post_ln, ref :630-634)
    - Falcon parallel attention: mlp input = same norm output, residual =
      hidden + attn_out + mlp_out (ref :774-806)
    - Falcon-40B parallel layernorm: dedicated mlp_norm (ref :613-629)
    """
    p_hidden = cfg.hidden_dropout if hidden_dropout_rate is None else hidden_dropout_rate
    if dropout_rng is not None:
        attn_rng, h1_rng, h2_rng = jax.random.split(dropout_rng, 3)
    else:
        attn_rng = h1_rng = h2_rng = None

    residual = hidden
    normed = apply_norm(hidden, layer_params["input_norm"], cfg)
    attn_out, new_cache = attention_block(
        layer_params["attention"], cfg, normed, rope_table, mask, position_ids,
        attn_rng, deterministic, kv_cache,
    )

    if cfg.parallel_attn:
        if cfg.parallel_layernorm:
            mlp_in = apply_norm(hidden, layer_params["mlp_norm"], cfg)
        else:
            mlp_in = normed
        mlp_out = mlp_block(layer_params["mlp"], cfg, mlp_in, h2_rng, deterministic)
        out = residual + _dropout(attn_out + mlp_out, p_hidden, h1_rng, deterministic)
    elif cfg.use_post_ln:
        x = residual + _dropout(attn_out, p_hidden, h1_rng, deterministic)
        x = shard_activation(x, "hidden_seq")
        x = apply_norm(x, layer_params["post_attention_norm"], cfg)
        mlp_out = mlp_block(layer_params["mlp"], cfg, x, h2_rng, deterministic)
        out = x + _dropout(mlp_out, p_hidden, h2_rng, deterministic)
        # final norm handled by caller; post-LN applies input_norm after attn
    else:
        x = residual + _dropout(attn_out, p_hidden, h1_rng, deterministic)
        # mid-layer norm/dropout region: seq-sharded under SP (the
        # reduce-scatter after the row-parallel wo, ref: layers.py:225-296)
        x = shard_activation(x, "hidden_seq")
        normed2 = apply_norm(x, layer_params["post_attention_norm"], cfg)
        mlp_out = mlp_block(layer_params["mlp"], cfg, normed2, h2_rng, deterministic)
        out = x + _dropout(mlp_out, p_hidden, h2_rng, deterministic)

    # layer boundary = norm/dropout region: under SP the saved residual is
    # seq-sharded over (context, model) — the per-layer memory / tp saving
    # the reference's SP exists for (ref: layers.py:225-296)
    out = shard_activation(out, "hidden_seq")
    return out, new_cache


def transformer_stack(
    layer_params: dict,
    cfg,
    hidden: jnp.ndarray,
    rope_table=None,
    mask=None,
    position_ids=None,
    dropout_rng=None,
    deterministic: bool = True,
    kv_caches: Optional[dict] = None,
    layer_offset: int = 0,
) -> Tuple[jnp.ndarray, Optional[dict]]:
    """Scan the stacked layers (ref: ParallelTransformer.forward
    transformer.py:1158-1246).

    `kv_caches` = {"k": (L,b,T,g,d), "v": ..., "offset": scalar} or None.
    `layer_offset` supports pipeline chunks (ref vpp offset math
    transformer.py:1015-1045): layer i's dropout key and LIMA rate use
    global index layer_offset + i.
    """
    unrolled = isinstance(layer_params, (list, tuple))
    L = len(layer_params) if unrolled \
        else jax.tree_util.tree_leaves(layer_params)[0].shape[0]
    num_total = cfg.num_layers

    def body(carry, xs):
        hidden, = carry
        params_l, idx, cache_l = xs
        if dropout_rng is not None:
            rng_l = jax.random.fold_in(dropout_rng, idx)
        else:
            rng_l = None
        if cfg.lima_dropout and num_total > 1:
            # linear ramp 0 -> hidden_dropout over depth (ref: transformer.py:964-971)
            p_l = cfg.hidden_dropout * idx.astype(jnp.float32) / (num_total - 1)
        else:
            p_l = None
        out, new_cache_l = transformer_layer(
            params_l, cfg, hidden, rope_table, mask, position_ids,
            rng_l, deterministic, cache_l, hidden_dropout_rate=p_l,
        )
        return (out,), new_cache_l

    # Which remat policy wraps the scan body (models/remat.py): "full"
    # saves only the boundary carry, "selective"/"offload" keep the named
    # matmul outputs (on device / in pinned host), "save_dots" keeps every
    # dot, "none" skips the wrapper. How MANY layers get it follows
    # --recompute_method (ref: arguments.py:616-630): "uniform" remats
    # every layer; "block" remats only the first recompute_num_layers —
    # the rest keep their activations, soaking up whatever HBM is left.
    policy = cfg.resolved_remat_policy
    if policy != "none":
        if cfg.recompute_method == "block":
            n_remat = min(cfg.recompute_num_layers, L)
        else:
            n_remat = L
    else:
        n_remat = 0
    body_ck = remat_wrap(body, policy)

    idxs = layer_offset + jnp.arange(L)
    if unrolled:
        # Decode fast path (prepare_decode_params): per-layer standalone
        # weight trees + per-layer (b, g, T, d) caches, layer loop
        # UNROLLED in Python. The scan form dynamic-slices every layer's
        # weights AND cache out of stacked buffers each token — a full
        # extra read+write of the weights and cache per step (traced on
        # v5e); standalone buffers are read in place.
        assert kv_caches is not None and (
            "k_layers" in kv_caches or "k_pages_layers" in kv_caches
        ), "unrolled (tuple) layer params are the decode fast path"
        if "k_pages_layers" in kv_caches:
            # paged serving (continuous-batching engine): per-layer page
            # POOLS with one shared page table + per-slot lengths; each
            # layer scatters its span into the slot's pages and reads
            # back only owned pages through THE ragged paged attention
            # kernel (attention_block's one paged branch, ISSUE 18 —
            # decode rows are width-1 chunks of the same kernel). Same
            # unrolled structure as the dense decode fast path —
            # standalone per-layer buffers, no stack slicing.
            pt = kv_caches["page_table"]
            lens = kv_caches["lengths"]
            # chunked mixed prefill+decode step (ISSUE 4): per-slot
            # ragged chunk lengths ride through every layer (the layer
            # branch scatters + attends the whole span at once); the
            # stack-level length advance is ragged too
            cl = kv_caches.get("chunk_lens")
            # packed multi-doc prefill (ISSUE 19): per-chunk document
            # floors thread through every layer exactly like chunk_lens
            dcs = kv_caches.get("doc_starts")
            ks = list(kv_caches["k_pages_layers"])
            vs = list(kv_caches["v_pages_layers"])
            # int8 KV pools (ISSUE 9): per-layer fp32 scale pools ride
            # alongside the data pools through every layer
            kss = (list(kv_caches["k_scales_layers"])
                   if "k_scales_layers" in kv_caches else None)
            vss = (list(kv_caches["v_scales_layers"])
                   if kss is not None else None)
            for i in range(L):
                cache_l = {"k_pages": ks[i], "v_pages": vs[i],
                           "page_table": pt, "lengths": lens}
                if cl is not None:
                    cache_l["chunk_lens"] = cl
                if dcs is not None:
                    cache_l["doc_starts"] = dcs
                if kss is not None:
                    cache_l["k_scales"] = kss[i]
                    cache_l["v_scales"] = vss[i]
                (hidden,), nc = body(
                    (hidden,), (layer_params[i], idxs[i], cache_l)
                )
                ks[i], vs[i] = nc["k_pages"], nc["v_pages"]
                if kss is not None:
                    kss[i], vss[i] = nc["k_scales"], nc["v_scales"]
            new_caches = {
                "k_pages_layers": tuple(ks), "v_pages_layers": tuple(vs),
                "page_table": pt,
                "lengths": lens + (cl if cl is not None
                                   else hidden.shape[1]),
            }
            if cl is not None:
                new_caches["chunk_lens"] = cl
            if dcs is not None:
                new_caches["doc_starts"] = dcs
            if kss is not None:
                new_caches["k_scales_layers"] = tuple(kss)
                new_caches["v_scales_layers"] = tuple(vss)
            return hidden, new_caches
        offset = kv_caches["offset"]
        ks = list(kv_caches["k_layers"])
        vs = list(kv_caches["v_layers"])
        for i in range(L):
            cache_l = {"k_gtd": ks[i], "v_gtd": vs[i], "offset": offset}
            (hidden,), nc = body(
                (hidden,), (layer_params[i], idxs[i], cache_l)
            )
            ks[i], vs[i] = nc["k_gtd"], nc["v_gtd"]
        new_caches = {"k_layers": tuple(ks), "v_layers": tuple(vs),
                      "offset": offset + hidden.shape[1]}
        return hidden, new_caches
    if kv_caches is not None:
        # Decode: the FULL (L, b, T, g, d) cache stacks ride the scan
        # CARRY and each layer updates its token column in place
        # (attention_block's stacked-cache form). The previous xs/ys form
        # re-materialized and re-stacked every layer's whole cache per
        # step — 2.2x slower per decode step (see attention.py).
        offset = kv_caches["offset"]

        def cache_body(carry, xs):
            hidden, kc, vc = carry
            params_l, idx = xs
            cache_l = {"k": kc, "v": vc, "offset": offset,
                       "layer": idx - layer_offset}
            (out,), new_cache_l = body((hidden,), (params_l, idx, cache_l))
            return (out, new_cache_l["k"], new_cache_l["v"]), None

        f = remat_wrap(cache_body, policy) if n_remat == L else cache_body
        (hidden, kc, vc), _ = jax.lax.scan(
            f, (hidden, kv_caches["k"], kv_caches["v"]),
            (layer_params, idxs),
        )
        new_caches = {"k": kc, "v": vc,
                      "offset": kv_caches["offset"] + hidden.shape[1]}
    else:
        xs = (layer_params, idxs, None)
        if 0 < n_remat < L:
            take = lambda tree, a, b: jax.tree.map(  # noqa: E731
                lambda x: x[a:b], tree
            )
            (hidden,), _ = jax.lax.scan(
                body_ck, (hidden,), take(xs, 0, n_remat)
            )
            (hidden,), _ = jax.lax.scan(
                body, (hidden,), take(xs, n_remat, L)
            )
        else:
            f = body_ck if n_remat == L else body
            (hidden,), _ = jax.lax.scan(f, (hidden,), xs)
        new_caches = None
    return hidden, new_caches
