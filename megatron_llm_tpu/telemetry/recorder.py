"""Flight recorder: a bounded ring of structured events + counter
snapshots, dumped to a JSON artifact when a run dies (ISSUE 13).

The failure modes this repo already survives — engine serve-loop poison
(engine.py `_fail_all`), loss-watchdog rollback (trainer.py
`_rollback`), SIGTERM preemption (the emergency save) — previously left
only a log tail. The recorder keeps the last N structured events (one
per scheduler round / train step / lifecycle transition, each carrying
the correlating `rid` or `step`) and periodic counter snapshots in
memory, and `dump()` writes them as one readable JSON artifact at the
moment of death, so the postmortem starts from "what was the engine
doing for the last 4096 rounds" instead of grepping stdout.

Recording is pure host bookkeeping (dict literal + deque append; the
emit path is listed in graft-check GR006 HOT_PATHS) and the ring is
bounded, so a recorder can stay on permanently — it is constructed by
default in both the engine and the trainer.

Artifact shape (tests/test_telemetry.py loads and correlates it):

    {"reason": "...", "dumped_at_unix": ..., "created_at_unix": ...,
     "pid": ..., "extra": {...},
     "events": [{"t": <unix>, "kind": "...", ...fields}, ...],
     "counters": {<last snapshot>}, "dropped_events": N}
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Optional

_logger = logging.getLogger(__name__)

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """Bounded structured-event ring with crash-dump export."""

    def __init__(self, capacity: int = 4096,
                 base: Optional[dict] = None):
        assert capacity >= 16, "a flight record needs some history"
        self.capacity = capacity
        # `base`: fields stamped into EVERY event (ISSUE 14: the
        # engine's replica id — N replicas' aggregated dumps must stay
        # attributable at the router). None keeps the event schema
        # byte-identical to the standalone recorder's.
        self._base = dict(base) if base else {}
        self._events: deque = deque(maxlen=capacity)
        # serializes ring mutation vs snapshot(): GET /flight_record
        # iterates the ring from an HTTP thread while the serve loop
        # appends — an unlocked list(deque) mid-append raises
        # RuntimeError exactly when the postmortem endpoint matters
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._counters_t: float = 0.0
        self._created = time.time()
        self._pid = os.getpid()
        self.dropped = 0
        self.dumps = 0
        # the most recent artifact dump() actually wrote (ISSUE 20):
        # the router attaches it to the eviction event when a replica
        # leaves rotation, so poison rotation and the auto-dump stop
        # being uncorrelated; stays None until a dump lands on disk
        self.last_dump_path: Optional[str] = None

    # -- emit (GR006 HOT_PATHS: host bookkeeping only) ---------------------

    def record(self, kind: str, **fields) -> None:
        """Append one structured event. Values must already be host
        scalars/strings — the recorder never touches a device value.
        The lock is uncontended on the hot path (snapshot() holds it
        only for a ring copy)."""
        ev = {"t": time.time(), "kind": kind, **self._base, **fields}
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(ev)

    def note_counters(self, counters: dict) -> None:
        """Attach the latest counter snapshot (the engine's counters()
        dict / the trainer's gauges) — the dump carries the last one."""
        snap = dict(counters)
        with self._lock:
            self._counters = snap
            self._counters_t = time.time()

    # -- export ------------------------------------------------------------

    def snapshot(self, reason: str = "on-demand",
                 extra: Optional[dict] = None) -> dict:
        with self._lock:
            events = list(self._events)
            counters = self._counters
            counters_t = self._counters_t
            dropped = self.dropped
        return {
            "reason": reason,
            "created_at_unix": self._created,
            "dumped_at_unix": time.time(),
            "pid": self._pid,
            "capacity": self.capacity,
            "dropped_events": dropped,
            "extra": extra or {},
            "counters": counters,
            "counters_at_unix": counters_t,
            "events": events,
        }

    def dump(self, directory: Optional[str], reason: str,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write the snapshot artifact into `directory` and log the
        path LOUDLY (a dying run's last useful line). Returns the path;
        None when no directory is configured (the snapshot is still
        logged in summary form so the information is not lost) or the
        write itself failed (a full disk must not mask the original
        failure with a second traceback)."""
        snap = self.snapshot(reason=reason, extra=extra)
        self.dumps += 1
        if not directory:
            _logger.error(
                "FLIGHT RECORDER (%s): no record dir configured — "
                "in-memory snapshot only (%d events, last: %s)",
                reason, len(snap["events"]),
                snap["events"][-1] if snap["events"] else None)
            return None
        try:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(
                directory,
                f"flight_record_{reason}_{self._pid}_{self.dumps}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(snap, fh, indent=1, default=str)
            os.replace(tmp, path)
        except OSError as e:
            _logger.error(
                "FLIGHT RECORDER (%s): dump to %s failed: %r — "
                "%d events lost to disk, kept in memory",
                reason, directory, e, len(snap["events"]))
            return None
        _logger.error(
            "FLIGHT RECORDER (%s): dumped %d events + counters to %s",
            reason, len(snap["events"]), path)
        self.last_dump_path = path
        return path
