"""Perf-regression sentinel: the loss watchdog's median+MAD machinery
pointed at latency instead of loss (ISSUE 15).

The loss watchdog (training/watchdog.py) catches a run whose MATH went
bad; nothing catches a run whose SPEED went bad — a step_ms or decode
round_ms that quietly doubles (thermal throttling, a neighbor VM, a
retrace storm that slipped past the contracts, a degrading host) burns
the same budget as a crash but never trips an alarm. `RobustWindow` is
the shared robust statistic (median + MAD over a sliding window — a
stall must not poison the estimate that should catch it, same argument
as the watchdog's); `PerfSentinel` applies it to a latency series:
`patience` consecutive observations above median + k_sigma * 1.4826*MAD
is a SUSTAINED regression — it emits a flight-recorder event trail,
trips a counter, and the owner (trainer / engine) auto-dumps the flight
ring through the same postmortem path as poison/rollback.

Emission is pure host arithmetic on floats the caller already fetched
(graft-check GR006 HOT_PATHS lists observe()); the sentinel never
touches a device value, so sentinel-on steps are bitwise sentinel-off.
"""

from __future__ import annotations

import collections
import math
from typing import Deque, Optional

__all__ = ["RobustWindow", "PerfSentinel"]


class RobustWindow:
    """Sliding window with a median+MAD threshold — the ONE robust
    statistic the loss watchdog and the perf sentinel share
    (training/watchdog.py delegates here)."""

    def __init__(self, window: int = 64, min_history: int = 8):
        assert window >= 4 and min_history >= 2
        # a window smaller than min_history could never arm the
        # threshold (the deque caps below it) — clamp so every accepted
        # window size actually detects
        self.min_history = min(min_history, window)
        self._window: Deque[float] = collections.deque(maxlen=window)

    def push(self, x: float) -> None:
        self._window.append(x)

    def clear(self) -> None:
        self._window.clear()

    def __len__(self) -> int:
        return len(self._window)

    def median_mad(self):
        xs = sorted(self._window)
        n = len(xs)
        med = (xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2]))
        dev = sorted(abs(x - med) for x in xs)
        mad = (dev[n // 2] if n % 2 else 0.5 * (dev[n // 2 - 1] + dev[n // 2]))
        return med, mad

    def threshold(self, k_sigma: float) -> float:
        """Value above which an observation is an outlier; +inf while
        disabled (k_sigma <= 0) or the window is too short to be
        trusted. 1.4826 * MAD estimates sigma for a normal population;
        the floor keeps a perfectly flat window (MAD 0) from flagging
        every observation."""
        if k_sigma <= 0 or len(self._window) < self.min_history:
            return math.inf
        med, mad = self.median_mad()
        sigma = max(1.4826 * mad, 1e-3 * abs(med), 1e-8)
        return med + k_sigma * sigma


class PerfSentinel:
    """Sustained-latency-regression detector with flight-record trail.

    `observe(value_ms, step=...)` feeds one latency sample; returns
    True exactly when this sample completes a TRIP (`patience`
    consecutive samples above threshold) — the caller dumps the flight
    ring on True. Good samples enter the window; bad samples never do
    (they would drag the baseline toward the regression). After a trip
    the window CLEARS: if the regression is the new normal (a slower
    chip, a permanent noisy neighbor) the sentinel re-arms at the new
    level instead of tripping forever, and the trip count records that
    the baseline moved.

    `k_sigma <= 0` disables the sentinel entirely (`enabled` False —
    owners skip construction-side costs and counters keys, keeping the
    /metrics JSON schema byte-compatible when off)."""

    def __init__(self, k_sigma: float = 0.0, window: int = 64,
                 patience: int = 8, min_history: int = 8,
                 recorder=None, name: str = "step_ms"):
        assert patience >= 1
        self.k_sigma = k_sigma
        self.patience = patience
        self.name = name
        # optional telemetry.FlightRecorder: every bad verdict and trip
        # lands in the flight ring keyed by step/round, so the dumped
        # artifact shows the latency trail that led to the trip
        self.recorder = recorder
        self._stat = RobustWindow(window=window, min_history=min_history)
        self.consecutive_bad = 0
        self.bad_total = 0
        self.trips = 0
        self.last_threshold = math.inf

    @property
    def enabled(self) -> bool:
        return self.k_sigma > 0

    def threshold(self) -> float:
        return self._stat.threshold(self.k_sigma)

    def observe(self, value_ms: float, step: int = -1) -> bool:
        """GR006 HOT_PATHS: host floats only — the caller already
        fetched/measured the latency."""
        if not self.enabled:
            return False
        thr = self._stat.threshold(self.k_sigma)
        self.last_threshold = thr
        if not (value_ms > thr):
            self.consecutive_bad = 0
            self._stat.push(value_ms)
            return False
        self.consecutive_bad += 1
        self.bad_total += 1
        if self.recorder is not None:
            self.recorder.record(
                f"perf_bad.{self.name}", step=step,
                value_ms=round(value_ms, 3), threshold_ms=round(thr, 3),
                streak=self.consecutive_bad)
        if self.consecutive_bad < self.patience:
            return False
        self.trips += 1
        self.consecutive_bad = 0
        med, mad = self._stat.median_mad()
        # re-arm at the new level: the post-trip window starts empty
        self._stat.clear()
        if self.recorder is not None:
            self.recorder.record(
                f"perf_regression.{self.name}", step=step,
                value_ms=round(value_ms, 3), threshold_ms=round(thr, 3),
                baseline_median_ms=round(med, 3),
                baseline_mad_ms=round(mad, 3),
                patience=self.patience, trip=self.trips)
        return True

    def counters(self) -> dict:
        return {f"perf_regressions_{self.name}": self.trips,
                f"perf_bad_{self.name}": self.bad_total}
