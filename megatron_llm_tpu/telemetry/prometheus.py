"""Histogram metrics + Prometheus text exposition (ISSUE 13).

The serving SLO contract (the Gemma fine-tune-and-serve paper,
PAPERS.md) is a latency DISTRIBUTION, not a point percentile: the
engine's `serve_ttft_p95_ms` gauge collapses the last 256 requests to
one number, which a scraping system can neither aggregate across
replicas nor re-quantile over time. This module adds real cumulative
histograms (fixed bucket bounds, monotone bucket counts, sum + count —
the Prometheus `histogram` type, aggregatable by summing buckets) and
renders them, plus the existing scalar counters, in the Prometheus text
exposition format (version 0.0.4) that GET /metrics serves under
content negotiation (inference/server.py; the legacy JSON schema stays
byte-compatible on the default path).

`Histogram.observe` is on the engine's per-token/per-request hot path:
it is a bisect + two increments on host floats, listed in graft-check
GR006 HOT_PATHS so it can never grow a device sync.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Tuple

__all__ = [
    "Histogram",
    "DEFAULT_LATENCY_BUCKETS_MS",
    "render_prometheus",
    "histograms_from_prometheus",
    "PROMETHEUS_CONTENT_TYPE",
]

# ms-denominated latency bounds: sub-ms decode rounds up to multi-minute
# stalls; roughly log-spaced like prometheus.ExponentialBuckets
DEFAULT_LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class Histogram:
    """Cumulative-bucket histogram (the Prometheus semantics: bucket
    `le=B` counts every observation <= B; `+Inf` == count)."""

    def __init__(self, name: str, buckets: Iterable[float] =
                 DEFAULT_LATENCY_BUCKETS_MS, help_text: str = ""):
        self.name = name
        self.help_text = help_text
        bounds = tuple(sorted(float(b) for b in buckets))
        assert bounds, "a histogram needs at least one finite bucket"
        self.bounds = bounds
        # per-bucket (non-cumulative) counts + one overflow cell; the
        # exposition accumulates — keeping raw cells makes observe O(1)
        # after the bisect instead of touching every higher bucket.
        # The lock keeps (cells, sum, count) consistent against a
        # concurrent scrape: an unsynchronized render mid-observe can
        # emit a finite bucket cumulative > the +Inf count — an invalid
        # Prometheus histogram strict consumers reject.
        self._lock = threading.Lock()
        self._cells: List[int] = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        # GR006 HOT_PATHS: host floats only — a jax scalar here would
        # be a per-token device sync (the lock is uncontended except
        # during a scrape's snapshot copy)
        idx = bisect_left(self.bounds, value)
        with self._lock:
            self._cells[idx] += 1
            self._sum += value
            self._count += 1

    def _snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._cells), self._sum, self._count

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(bound, cumulative_count), ...] + (inf, count) — one
        consistent snapshot (+Inf always equals the total count)."""
        cells, _, count = self._snapshot()
        out, acc = [], 0
        for b, c in zip(self.bounds, cells):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), count))
        return out

    @classmethod
    def from_cumulative(cls, name: str, cumulative, total: float,
                        count: int, help_text: str = "") -> "Histogram":
        """Rebuild a Histogram from its exposition form — [(bound,
        cumulative_count), ...] WITHOUT the +Inf row, plus sum and
        count. The inverse of `cumulative()`/`to_prom_lines()`: raw
        cells are first-differences of the cumulative counts, the
        overflow cell is count - last cumulative. This is how the
        replica router rebuilds a REMOTE replica's distributions from
        its scraped /metrics text (ISSUE 15: HTTPReplica histogram
        proxying) so `merged` can fold them into the fleet view."""
        pairs = sorted((float(b), int(c)) for b, c in cumulative)
        bounds = tuple(b for b, _ in pairs)
        h = cls(name, buckets=bounds, help_text=help_text)
        prev = 0
        cells = []
        for _, c in pairs:
            if c < prev:
                raise ValueError(
                    f"histogram {name!r}: non-monotone cumulative "
                    f"bucket counts {pairs} — not a valid Prometheus "
                    f"histogram")
            cells.append(c - prev)
            prev = c
        overflow = int(count) - prev
        if overflow < 0:
            raise ValueError(
                f"histogram {name!r}: count {count} below the last "
                f"finite bucket's cumulative {prev}")
        h._cells = cells + [overflow]
        h._sum = float(total)
        h._count = int(count)
        return h

    @classmethod
    def merged(cls, histograms: Iterable["Histogram"]) -> "Histogram":
        """Sum same-named, same-bucket histograms from N engines into
        one — cumulative buckets are additive by design (the module
        docstring's aggregatability claim made executable): the
        replica router's GET /metrics serves fleet-wide TTFT/decode
        distributions this way (inference/router.py, ISSUE 14)."""
        hs = list(histograms)
        assert hs, "merged() needs at least one histogram"
        first = hs[0]
        out = cls(first.name, buckets=first.bounds,
                  help_text=first.help_text)
        for h in hs:
            assert h.name == first.name and h.bounds == first.bounds, (
                "merging histograms with different names/buckets would "
                "fabricate a distribution", h.name, first.name)
            cells, s, c = h._snapshot()
            out._cells = [a + b for a, b in zip(out._cells, cells)]
            out._sum += s
            out._count += c
        return out

    def to_prom_lines(self, prefix: str = "") -> List[str]:
        name = prefix + self.name
        lines = []
        if self.help_text:
            lines.append(f"# HELP {name} {self.help_text}")
        lines.append(f"# TYPE {name} histogram")
        cells, total, count = self._snapshot()
        acc = 0
        for b, c in zip(self.bounds, cells):
            acc += c
            lines.append(f'{name}_bucket{{le="{_fmt(b)}"}} {acc}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{name}_sum {_fmt(total)}")
        lines.append(f"{name}_count {count}")
        return lines


def _fmt(v) -> str:
    """Prometheus float formatting: integral values without the .0
    noise, everything else repr-exact."""
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def render_prometheus(counters: Dict, histograms: Iterable[Histogram] = (),
                      prefix: str = "", info_name: str = "build_info",
                      ) -> str:
    """One Prometheus text page from a flat counters dict (the engine's
    `counters()` / the trainer's gauges) plus histogram objects.

    Numeric values become gauges under their (sanitized) key; string
    values — e.g. `serve_kv_dtype` — collapse into ONE info-style
    metric (`<prefix><info_name>{key="value", ...} 1`), the Prometheus
    idiom for non-numeric facts; other types are skipped rather than
    guessed at."""
    lines: List[str] = []
    info_labels: List[str] = []
    for key in counters:
        value = counters[key]
        name = prefix + _sanitize(key)
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            if isinstance(value, str):
                esc = value.replace("\\", "\\\\").replace('"', '\\"')
                info_labels.append(f'{_sanitize(key)}="{esc}"')
            continue
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(value)}")
    if info_labels:
        iname = prefix + info_name
        lines.append(f"# TYPE {iname} gauge")
        lines.append(f"{iname}{{{','.join(info_labels)}}} 1")
    for h in histograms:
        lines.extend(h.to_prom_lines(prefix))
    return "\n".join(lines) + "\n"


def histograms_from_prometheus(text: str) -> List[Histogram]:
    """Reconstruct every histogram-typed metric in a Prometheus text
    page (the inverse of `to_prom_lines`): `# TYPE <name> histogram`
    declares one, its `<name>_bucket{le=...}` samples carry the
    cumulative counts, `<name>_sum`/`<name>_count` the totals. Used by
    `inference/router.HTTPReplica` to merge REMOTE replicas' latency
    distributions into the fleet /metrics (ISSUE 15 — closing the
    PR-14 documented gap that merged histograms covered in-process
    replicas only). Malformed sections raise ValueError — a fleet view
    silently missing one replica's distribution would misstate the
    SLO."""
    hist_names: List[str] = []
    for line in text.splitlines():
        parts = line.strip().split()
        if len(parts) == 4 and parts[0] == "#" and parts[1] == "TYPE" \
                and parts[3] == "histogram":
            hist_names.append(parts[2])
    if not hist_names:
        return []
    samples = parse_prometheus(text)
    out: List[Histogram] = []
    for name in hist_names:
        buckets = samples.get(f"{name}_bucket", {})
        cumulative = []
        count = None
        for labels, value in buckets.items():
            le = None
            for part in labels.split(","):
                k, _, v = part.partition("=")
                if k.strip() == "le":
                    le = v.strip().strip('"')
            if le is None:
                raise ValueError(
                    f"histogram {name!r}: bucket sample without an le "
                    f"label ({labels!r})")
            if le in ("+Inf", "inf", "Inf"):
                count = int(value)
            else:
                cumulative.append((float(le), int(value)))
        total = samples.get(f"{name}_sum", {}).get("")
        n = samples.get(f"{name}_count", {}).get("")
        if count is None:
            count = int(n) if n is not None else None
        if count is None or total is None or not cumulative:
            raise ValueError(
                f"histogram {name!r}: incomplete exposition (buckets="
                f"{len(cumulative)}, sum={total}, count={count})")
        out.append(Histogram.from_cumulative(
            name, cumulative, total, count))
    return out


def parse_prometheus(text: str) -> Dict[str, Dict]:
    """Tiny exposition parser for tests/bench self-checks: returns
    {metric_name: {"labels...": value}} with the bare sample keyed "".
    Not a general client — enough to verify our own rendering."""
    out: Dict[str, Dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, val = line.rpartition(" ")
        if "{" in name_part:
            name, _, labels = name_part.partition("{")
            labels = labels.rstrip("}")
        else:
            name, labels = name_part, ""
        out.setdefault(name, {})[labels] = float(val)
    return out
