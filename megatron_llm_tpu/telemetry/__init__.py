"""Flight-recorder telemetry (ISSUE 13): structured tracing, Prometheus
metrics, and postmortem recording across the train and serve hot paths.

Three pieces, one contract:

- `trace.SpanTracer` — nestable host-side spans with request-id /
  train-step correlation, exported as Chrome trace-event JSON
  (Perfetto);
- `recorder.FlightRecorder` — a bounded ring of structured events +
  counter snapshots, auto-dumped to a JSON artifact on engine poison,
  watchdog rollback and SIGTERM emergency save;
- `prometheus.Histogram` / `render_prometheus` — real histogram metrics
  (TTFT, decode-round ms, queue wait, step ms) behind the
  content-negotiated Prometheus text exposition on GET /metrics.

The contract that keeps this subsystem honest: ALL emission stays
outside jitted code. Telemetry-on steps are bitwise-identical to
telemetry-off — pinned by tests/test_telemetry.py AND by the
graft-check audit (telemetry-on engine / train.step specializations
lower to the same collective inventory with zero host callbacks), and
the emit methods sit on graft-check GR006 HOT_PATHS so a device sync
can never creep into per-round bookkeeping.
"""

from megatron_llm_tpu.telemetry.prometheus import (
    DEFAULT_LATENCY_BUCKETS_MS,
    PROMETHEUS_CONTENT_TYPE,
    Histogram,
    parse_prometheus,
    render_prometheus,
)
from megatron_llm_tpu.telemetry.recorder import FlightRecorder
from megatron_llm_tpu.telemetry.trace import NULL_TRACER, SpanTracer

__all__ = [
    "SpanTracer",
    "NULL_TRACER",
    "FlightRecorder",
    "Histogram",
    "DEFAULT_LATENCY_BUCKETS_MS",
    "PROMETHEUS_CONTENT_TYPE",
    "render_prometheus",
    "parse_prometheus",
]
