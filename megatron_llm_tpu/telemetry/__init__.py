"""Flight-recorder telemetry (ISSUE 13): structured tracing, Prometheus
metrics, and postmortem recording across the train and serve hot paths.

Three pieces, one contract:

- `trace.SpanTracer` — nestable host-side spans with request-id /
  train-step correlation, exported as Chrome trace-event JSON
  (Perfetto);
- `recorder.FlightRecorder` — a bounded ring of structured events +
  counter snapshots, auto-dumped to a JSON artifact on engine poison,
  watchdog rollback and SIGTERM emergency save;
- `prometheus.Histogram` / `render_prometheus` — real histogram metrics
  (TTFT, decode-round ms, queue wait, step ms) behind the
  content-negotiated Prometheus text exposition on GET /metrics.

ISSUE 15 adds the device-cost layer on top:

- `chipspec.ChipSpec` / `detect_chip` — the TPU generation spec table
  (per-chip peak FLOP/s, HBM bytes/s) bench and the runtime both read;
- `costs.CostRegistry` — compiled-cost capture (cost_analysis FLOPs /
  bytes + memory_analysis temp/args) at jit-mint time, keyed by
  compile-contract name + specialization;
- `goodput.GoodputLedger` — the trainer's exclusive wall-time
  partition (productive / compile / checkpoint / data_wait / watchdog
  / idle, provably summing to wall);
- `sentinel.PerfSentinel` — the loss watchdog's median+MAD machinery
  pointed at step/round latency, auto-dumping the flight ring on a
  sustained regression.

The contract that keeps this subsystem honest: ALL emission stays
outside jitted code. Telemetry-on steps are bitwise-identical to
telemetry-off — pinned by tests/test_telemetry.py AND by the
graft-check audit (telemetry-on engine / train.step specializations
lower to the same collective inventory with zero host callbacks), and
the emit methods sit on graft-check GR006 HOT_PATHS so a device sync
can never creep into per-round bookkeeping.
"""

from megatron_llm_tpu.telemetry.chipspec import ChipSpec, detect_chip
from megatron_llm_tpu.telemetry.costs import CostRecord, CostRegistry
from megatron_llm_tpu.telemetry.goodput import (
    GOODPUT_BUCKETS,
    GoodputLedger,
)
from megatron_llm_tpu.telemetry.prometheus import (
    DEFAULT_LATENCY_BUCKETS_MS,
    PROMETHEUS_CONTENT_TYPE,
    Histogram,
    histograms_from_prometheus,
    parse_prometheus,
    render_prometheus,
)
from megatron_llm_tpu.telemetry.recorder import FlightRecorder
from megatron_llm_tpu.telemetry.sentinel import PerfSentinel, RobustWindow
from megatron_llm_tpu.telemetry.trace import NULL_TRACER, SpanTracer

__all__ = [
    "SpanTracer",
    "NULL_TRACER",
    "FlightRecorder",
    "Histogram",
    "DEFAULT_LATENCY_BUCKETS_MS",
    "PROMETHEUS_CONTENT_TYPE",
    "render_prometheus",
    "parse_prometheus",
    "histograms_from_prometheus",
    "ChipSpec",
    "detect_chip",
    "CostRecord",
    "CostRegistry",
    "GoodputLedger",
    "GOODPUT_BUCKETS",
    "PerfSentinel",
    "RobustWindow",
]
