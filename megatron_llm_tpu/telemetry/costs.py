"""Compiled-cost registry: FLOPs/bytes/temp-memory per jitted entry
point, captured at mint time (ISSUE 15).

The compile-contract registry (analysis/contracts.py) already knows
WHICH executables exist; this module records what each one COSTS —
`cost_analysis()` FLOPs and bytes-accessed from the lowering, plus
`memory_analysis()` temp/argument bytes from the compile — keyed by
contract name + specialization key, so the runtime can answer "what
device work does one dispatch of this executable represent" without a
profiler attached. Consumers:

- the trainer's goodput ledger turns the train.step record into a live
  MFU gauge (registry FLOPs x productive steps / wall / chipspec peak)
  and a per-executable achieved-GB/s roofline gauge;
- the engine's dispatch-overhead gauge compares each round's modeled
  device seconds (the record's roofline time on the detected chip)
  against the measured round wall;
- `tools/graft_check.py costs` diffs the audit's per-contract FLOPs and
  temp bytes against a checked-in baseline so a silent 2x FLOPs
  regression in any jitted entry point fails CI loudly.

The capture contract (GR006-enforced): capture happens at MINT time
only — once per (contract, specialization), never in the per-round /
per-step hot loop. `attach()` hooks the contract registry's mint
listener so the pending inventory mirrors record_variant exactly; the
owner (engine, trainer) then calls `capture()` with example args at the
same mint site. The hot loop only ever calls `record()` /
`CostRecord.modeled_seconds` — pure dict lookups and host arithmetic,
listed in graft-check GR006 HOT_PATHS.

Capture cost: `fn.lower(*args)` is an abstract trace (no XLA compile)
and yields cost_analysis; `capture_memory=True` additionally compiles
the lowering for memory_analysis — on this JAX line that compile does
NOT populate the jit call cache, so it is one EXTRA full compile per
minted executable. That is why the registry is opt-in
(`--device_cost_registry`, engine `cost_registry=True`), exactly like
the trainer's --log_memory_to_tensorboard relower.
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from megatron_llm_tpu.analysis import contracts as _contracts

__all__ = ["CostRecord", "CostRegistry"]


def _key_str(key: Any) -> str:
    return repr(key)


@dataclass
class CostRecord:
    """The captured device-cost facts of ONE minted executable."""

    contract: str
    key: str
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    temp_bytes: Optional[int] = None
    arg_bytes: Optional[int] = None
    source: str = "lowered"  # "lowered" | "compiled"
    captured_unix: float = field(default_factory=time.time)

    def modeled_seconds(self, chip, n_chips: int = 1,
                        dtype: str = "bf16") -> Optional[float]:
        """Roofline device time for one execution on `chip`
        (telemetry/chipspec.ChipSpec): max of the compute leg
        (flops / peak) and the memory leg (bytes / HBM rate), across
        `n_chips` chips. None when the record or chip cannot support
        the estimate — callers drop their gauge instead of guessing.
        GR006 HOT_PATHS: pure host arithmetic (the engine calls this
        per round)."""
        if chip is None:
            return None
        legs = []
        if self.flops:
            legs.append(self.flops / (chip.peak_flops_for(dtype)
                                      * max(n_chips, 1)))
        if self.bytes_accessed:
            legs.append(self.bytes_accessed / (chip.hbm_bytes_s
                                               * max(n_chips, 1)))
        return max(legs) if legs else None

    def to_dict(self) -> dict:
        return {
            "contract": self.contract, "key": self.key,
            "flops": self.flops, "bytes_accessed": self.bytes_accessed,
            "temp_bytes": self.temp_bytes, "arg_bytes": self.arg_bytes,
            "source": self.source,
        }


def _analysis_dict(analysis) -> dict:
    """cost_analysis() returns a dict (Lowered) or a 1-list of dicts
    (Compiled) depending on the stage/backend — normalize."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return dict(analysis or {})


class CostRegistry:
    """Mint-time cost capture keyed by (contract, specialization).

    `owner`: when set, the mint listener only tracks variants minted
    under that contract owner (an engine instance tracks its own mints,
    not a sibling replica's); None tracks every mint.
    """

    def __init__(self, chip=None, capture_memory: bool = True,
                 owner: Any = None):
        self.chip = chip
        self.capture_memory = capture_memory
        self._owner_ref = (weakref.ref(owner) if owner is not None
                           else None)
        self._lock = threading.Lock()
        self._records: Dict[tuple, CostRecord] = {}
        # mint inventory from the contracts hook: every (name, key)
        # record_variant accepted, whether or not costs are captured
        # yet — the "registry knows what exists" half of the story
        self._pending: Dict[tuple, float] = {}
        self._listener = None
        self.captures = 0
        self.capture_errors = 0

    # -- the record_variant hook (mint-time inventory) ---------------------

    def attach(self) -> "CostRegistry":
        """Install the mint listener on analysis/contracts.py: every
        NEW variant record_variant accepts lands in the pending
        inventory. Idempotent; the listener holds only a weakref to
        this registry so a dropped registry never pins itself alive
        through the module-global listener list."""
        if self._listener is not None:
            return self
        ref = weakref.ref(self)
        owner_ref = self._owner_ref

        def _on_mint(name, key, owner, _ref=ref, _owner_ref=owner_ref):
            reg = _ref()
            if reg is None:
                # the registry (and its engine/trainer) died without
                # detach(): remove THIS closure from the module-global
                # listener list so cycled owners can never accumulate
                # dead entries (a long-lived process restarting replica
                # fleets would otherwise leak one per registry)
                _contracts.remove_mint_listener(_on_mint)
                return
            if _owner_ref is not None and owner is not _owner_ref():
                return
            reg.note_mint(name, key)

        self._listener = _on_mint
        _contracts.add_mint_listener(_on_mint)
        return self

    def detach(self) -> None:
        if self._listener is not None:
            _contracts.remove_mint_listener(self._listener)
            self._listener = None

    def note_mint(self, name: str, key: Any) -> None:
        with self._lock:
            self._pending.setdefault((name, _key_str(key)), time.time())

    # -- capture (mint-time only — never per-round) ------------------------

    def capture(self, name: str, key: Any, fn, args: tuple,
                kwargs: Optional[dict] = None) -> Optional[CostRecord]:
        """Capture the cost facts of one minted executable from its
        jitted fn + example args. The lowering is an abstract trace
        (cheap); with capture_memory the compile for memory_analysis is
        one EXTRA full compile (module docstring) — both are mint-time
        one-offs. Errors are swallowed into `capture_errors`: cost
        observability must never take a mint down."""
        try:
            lowered = fn.lower(*args, **(kwargs or {}))
            rec = CostRecord(contract=name, key=_key_str(key))
            try:
                ca = _analysis_dict(lowered.cost_analysis())
                rec.flops = float(ca["flops"]) if "flops" in ca else None
                if "bytes accessed" in ca:
                    rec.bytes_accessed = float(ca["bytes accessed"])
            except Exception:  # noqa: BLE001 — backend without analysis
                pass
            if self.capture_memory:
                compiled = lowered.compile()
                rec.source = "compiled"
                try:
                    mem = compiled.memory_analysis()
                    rec.temp_bytes = int(mem.temp_size_in_bytes)
                    rec.arg_bytes = int(mem.argument_size_in_bytes)
                except Exception:  # noqa: BLE001
                    pass
                if rec.flops is None:
                    ca = _analysis_dict(compiled.cost_analysis())
                    rec.flops = (float(ca["flops"])
                                 if "flops" in ca else None)
                    if "bytes accessed" in ca:
                        rec.bytes_accessed = float(ca["bytes accessed"])
        except Exception:  # noqa: BLE001
            with self._lock:
                self.capture_errors += 1
            return None
        return self._store(rec)

    def capture_compiled(self, name: str, key: Any,
                         compiled) -> Optional[CostRecord]:
        """Capture from an already-compiled artifact (the audit and the
        trainer's step-0 relower hold one) — no extra compile."""
        rec = CostRecord(contract=name, key=_key_str(key),
                         source="compiled")
        try:
            ca = _analysis_dict(compiled.cost_analysis())
            rec.flops = float(ca["flops"]) if "flops" in ca else None
            if "bytes accessed" in ca:
                rec.bytes_accessed = float(ca["bytes accessed"])
            mem = compiled.memory_analysis()
            rec.temp_bytes = int(mem.temp_size_in_bytes)
            rec.arg_bytes = int(mem.argument_size_in_bytes)
        except Exception:  # noqa: BLE001 — partial facts still useful
            pass
        return self._store(rec)

    def _store(self, rec: CostRecord) -> CostRecord:
        with self._lock:
            self._records[(rec.contract, rec.key)] = rec
            self._pending.pop((rec.contract, rec.key), None)
            self.captures += 1
        return rec

    # -- hot-loop reads (GR006 HOT_PATHS: host lookups only) ---------------

    def record(self, name: str, key: Any = None) -> Optional[CostRecord]:
        """The record for (contract, specialization); with key=None,
        any record under the contract (single-specialization
        contracts). Pure dict lookup — the engine's per-round
        dispatch-overhead accounting calls this."""
        if key is not None:
            return self._records.get((name, _key_str(key)))
        for (n, _k), rec in self._records.items():
            if n == name:
                return rec
        return None

    # -- export ------------------------------------------------------------

    def rows(self) -> List[dict]:
        with self._lock:
            recs = sorted(self._records.values(),
                          key=lambda r: (r.contract, r.key))
            pending = sorted(k for k in self._pending)
        out = [r.to_dict() for r in recs]
        out.extend({"contract": n, "key": k, "pending": True}
                   for n, k in pending)
        return out

    def snapshot(self) -> dict:
        """Flight-recorder / /metrics attachment: the whole table plus
        capture health."""
        return {
            "chip": self.chip.label() if self.chip else None,
            "captures": self.captures,
            "capture_errors": self.capture_errors,
            "records": self.rows(),
        }

    def prometheus_lines(self, prefix: str = "") -> List[str]:
        """Labeled Prometheus gauges for the /metrics text exposition:
        one sample per (contract, specialization) per fact — the
        labeled form a scraper can alert on per entry point."""
        metrics = (("cost_flops", "flops"),
                   ("cost_bytes_accessed", "bytes_accessed"),
                   ("cost_temp_bytes", "temp_bytes"),
                   ("cost_arg_bytes", "arg_bytes"))
        with self._lock:
            recs = sorted(self._records.values(),
                          key=lambda r: (r.contract, r.key))
        lines: List[str] = []
        for mname, attr in metrics:
            samples = []
            for r in recs:
                v = getattr(r, attr)
                if v is None:
                    continue
                key = r.key.replace("\\", "\\\\").replace('"', '\\"')
                samples.append(
                    f'{prefix}{mname}{{contract="{r.contract}",'
                    f'key="{key}"}} {v:g}')
            if samples:
                lines.append(f"# TYPE {prefix}{mname} gauge")
                lines.extend(samples)
        return lines
