"""Goodput ledger: every second of trainer wall time classified into
exclusive buckets that provably sum to wall (ISSUE 15).

"23.5k tok/s at MFU 55%" describes the steady state; a production run's
bill is dominated by everything else — trace/compile stalls, checkpoint
waits, data-loader hiccups, watchdog skips, plain idleness. The Goodput
literature (and the pjit-TPUv4 paper's utilization framing, PAPERS.md)
prices a run as productive_time / wall_time; that requires an exclusive
partition of wall, not a pile of overlapping timers. This ledger is
that partition:

- `productive` — optimizer steps that landed (dispatch + loss fetch);
- `compile`    — the first execution of each train-step specialization
  (trace + XLA compile ride the first call on this JAX line; the
  bucket's semantics are "the step that paid the compile", first
  productive execution included — docs/GUIDE.md states the caveat);
- `checkpoint` — save dispatch + async-tail/commit waits + rollback
  reload stalls;
- `data_wait`  — blocking next() on the data iterator;
- `watchdog`   — steps the loss watchdog skipped (the device discarded
  the update: the wall was spent, the step bought nothing);
- `idle`       — everything else (logging, eval, scheduler host work,
  genuine idleness), DERIVED as wall - sum(explicit buckets), which is
  what makes the sum-to-wall invariant hold by construction.

`note()` is one float add on the host (graft-check GR006 HOT_PATHS);
the ledger never touches a device value, so ledger-on training is
bitwise ledger-off. If explicit buckets ever overlap-count past wall
(a bug in the caller's classification), `overcount_s` goes positive
instead of silently clamping — the invariant test pins it at 0.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

__all__ = ["GOODPUT_BUCKETS", "GoodputLedger"]

# the exclusive wall-time partition; "idle" is derived, never noted
GOODPUT_BUCKETS = ("productive", "compile", "checkpoint", "data_wait",
                   "watchdog", "idle")


class GoodputLedger:
    """Exclusive wall-time accounting for a host-driven loop."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._t0: Optional[float] = None
        self._acc: Dict[str, float] = {
            b: 0.0 for b in GOODPUT_BUCKETS if b != "idle"}
        self.productive_steps = 0

    def start(self) -> None:
        """Start (or restart) the wall clock. Idempotent-by-intent:
        the first call pins t0; a second call is a no-op so nested
        callers cannot reset a running ledger's wall."""
        if self._t0 is None:
            self._t0 = self._clock()

    @property
    def started(self) -> bool:
        return self._t0 is not None

    def note(self, bucket: str, seconds: float) -> None:
        """Attribute `seconds` of wall to one explicit bucket. GR006
        HOT_PATHS: one dict add on host floats, called once or twice
        per trainer iteration."""
        if bucket == "idle":
            raise ValueError(
                "'idle' is derived (wall - sum of explicit buckets) — "
                "noting it would double-count the remainder")
        self._acc[bucket] += seconds  # KeyError on unknown = loud
        if bucket == "productive":
            self.productive_steps += 1

    def wall_s(self) -> float:
        return 0.0 if self._t0 is None else self._clock() - self._t0

    def snapshot(self) -> dict:
        """The partition at this instant. Invariant (pinned by
        tests/test_goodput.py): sum(buckets.values()) == wall_s exactly
        — idle is the derived remainder; if the explicit buckets
        overcounted past wall, idle floors at 0 and `overcount_s`
        carries the excess so the books never silently balance."""
        wall = self.wall_s()
        explicit = dict(self._acc)
        total = sum(explicit.values())
        idle = wall - total
        overcount = max(-idle, 0.0)
        buckets = {**explicit, "idle": max(idle, 0.0)}
        return {
            "wall_s": round(wall, 6),
            "buckets": {b: round(buckets[b], 6) for b in GOODPUT_BUCKETS},
            "goodput_fraction": round(
                buckets["productive"] / wall, 6) if wall > 0 else 0.0,
            "productive_steps": self.productive_steps,
            "overcount_s": round(overcount, 6),
        }

    def counters(self, prefix: str = "goodput_") -> dict:
        """Flat gauge form for the timers-gauge ride-along / Prometheus
        rendering: cumulative seconds per bucket plus the headline
        fraction."""
        snap = self.snapshot()
        out = {f"{prefix}{b}_s": round(v, 3)
               for b, v in snap["buckets"].items()}
        out[f"{prefix}wall_s"] = round(snap["wall_s"], 3)
        out[f"{prefix}fraction"] = round(snap["goodput_fraction"], 4)
        return out
