"""TPU generation spec table: the ONE source of peak-FLOP/s and HBM
numbers for MFU and roofline math (ISSUE 15).

Before this module, the hardware peaks lived as constants inside
bench.py (`V5E_PEAK_BF16`, `V5E_HBM_BYTES_S`) — invisible at runtime,
so nothing live could say "this step ran at 54% MFU" or "this decode
round achieved 62% of HBM line rate". The pjit-TPUv4 paper (PAPERS.md)
makes hardware utilization the headline metric for exactly this class
of system; that requires the peaks to be a runtime fact, not a bench
comment. Both bench and the runtime (trainer goodput ledger, engine
dispatch-overhead gauge, CostRegistry roofline math) now read THIS
table.

Detection reads `jax.devices()[0].device_kind` (lazy jax import — this
module itself stays import-light for the telemetry package). Because
device_kind strings drift across libtpu releases ("TPU v5 lite" vs
"TPU v5e"), matching is substring-based and an explicit `override`
(CLI `--chip_spec`, engine `chip_spec=`, env `MEGATRON_TPU_CHIPSPEC`)
always wins — on the CPU test harness the override is the only way to
get deterministic MFU/roofline numbers at all.

Peak numbers are the published per-chip figures:
- v5e: 197 TFLOP/s bf16, 394 TOP/s int8, 819 GB/s HBM, 16 GiB
- v5p: 459 TFLOP/s bf16, 918 TOP/s int8, 2765 GB/s HBM, 95 GiB
- v4:  275 TFLOP/s bf16, 275 TOP/s int8, 1228 GB/s HBM, 32 GiB
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import Mapping, Optional, Tuple

__all__ = [
    "ChipSpec",
    "CHIP_SPECS",
    "detect_chip",
    "train_flops_per_token",
    "decode_flops_per_token",
]


@dataclass(frozen=True)
class ChipSpec:
    """Per-chip peaks for one TPU generation.

    `source` records how this spec was chosen ("detected", "override",
    or "assumed") so every gauge/bench row that cites it can state
    whether the denominator was measured-at-runtime or asserted by the
    operator — an MFU number against an assumed chip is a different
    claim than one against the detected chip.
    """

    name: str
    peak_flops: Mapping[str, float]  # dtype family -> per-chip FLOP/s
    hbm_bytes_s: float  # per-chip HBM bandwidth
    hbm_bytes: int  # per-chip HBM capacity
    source: str = "table"

    def peak_flops_for(self, dtype: str = "bf16") -> float:
        """Peak FLOP/s for a compute dtype. fp32 maps to the bf16 MXU
        peak (the MXU multiplies bf16 with fp32 accumulation; an fp32
        model's matmuls still ride it on these generations), int8 to
        the int8 peak."""
        d = str(dtype).lower()
        if "int8" in d:
            return self.peak_flops.get("int8", self.peak_flops["bf16"])
        return self.peak_flops["bf16"]

    def label(self) -> str:
        return f"{self.name}:{self.source}"


CHIP_SPECS: Mapping[str, ChipSpec] = {
    "v5e": ChipSpec(
        name="v5e",
        peak_flops={"bf16": 197e12, "int8": 394e12},
        hbm_bytes_s=819e9,
        hbm_bytes=16 * 2**30,
    ),
    "v5p": ChipSpec(
        name="v5p",
        peak_flops={"bf16": 459e12, "int8": 918e12},
        hbm_bytes_s=2765e9,
        hbm_bytes=95 * 2**30,
    ),
    "v4": ChipSpec(
        name="v4",
        peak_flops={"bf16": 275e12, "int8": 275e12},
        hbm_bytes_s=1228e9,
        hbm_bytes=32 * 2**30,
    ),
}

# device_kind substring -> table key, first match wins (order matters:
# "v5 lite"/"v5e" must be tried before the bare "v5" of v5p kinds)
_KIND_PATTERNS: Tuple[Tuple[str, str], ...] = (
    ("v5 lite", "v5e"),
    ("v5litepod", "v5e"),
    ("v5e", "v5e"),
    ("v5p", "v5p"),
    ("v5", "v5p"),
    ("v4", "v4"),
)

_ENV_OVERRIDE = "MEGATRON_TPU_CHIPSPEC"


def detect_chip(devices=None, override: Optional[str] = None,
                default: Optional[str] = None) -> Optional[ChipSpec]:
    """Resolve the chip spec: explicit `override` (or the
    MEGATRON_TPU_CHIPSPEC env var) wins, then detection from the device
    kind, then `default` (source marked "assumed"), then None — a None
    return means "no credible denominator": callers must drop their
    MFU/roofline gauges rather than report against a guessed peak.

    `devices`: the device subset the caller actually computes on (an
    engine pinned to a replica's devices); None = jax.devices(). jax is
    imported lazily and a CPU/import failure falls through to
    `default`."""
    override = override or os.environ.get(_ENV_OVERRIDE) or None
    if override:
        key = str(override).lower()
        if key not in CHIP_SPECS:
            raise ValueError(
                f"unknown chip spec {override!r} "
                f"(known: {sorted(CHIP_SPECS)}) — extend the table in "
                f"telemetry/chipspec.py for a new generation")
        return replace(CHIP_SPECS[key], source="override")
    kind = ""
    try:
        if devices is None:
            import jax

            devices = jax.devices()
        if devices:
            kind = str(getattr(devices[0], "device_kind", "")).lower()
    except Exception:  # noqa: BLE001 — no jax / no devices: fall through
        kind = ""
    if "tpu" in kind or kind.startswith("v"):
        for pat, key in _KIND_PATTERNS:
            if pat in kind:
                return replace(CHIP_SPECS[key], source="detected")
    if default is not None:
        return replace(CHIP_SPECS[str(default).lower()], source="assumed")
    return None


def train_flops_per_token(n_params: int, num_layers: int,
                          hidden_size: int, seq_length: int) -> float:
    """fwd+bwd model FLOPs per trained token: 6*N for the matmuls plus
    causal attention (12*L*h*s per token fwd+bwd with the 1/2 causal
    discount = 6*L*h*s). The ONE definition bench MFU and the trainer's
    live MFU gauge share — they must never disagree about the
    numerator."""
    return 6.0 * n_params + 6.0 * num_layers * hidden_size * seq_length


def decode_flops_per_token(n_params: int, num_layers: int,
                           hidden_size: int, context: int) -> float:
    """fwd-only model FLOPs for one decoded token at cache length
    `context`: 2*N for the matvecs plus attention reading the cache
    (QK^T + PV = 4*L*h*context). The engine's per-request modeled-FLOPs
    record integrates this over the request's context growth."""
    return 2.0 * n_params + 4.0 * num_layers * hidden_size * context
