"""Host-side span tracer: Chrome trace-event JSON for Perfetto (ISSUE 13).

The serving engine and the trainer are host-driven schedulers around
jitted dispatches; diagnosing a stall ("why did request 41's TTFT blow
up at 02:13?") needs the host timeline — queue wait, admission, chunk
prefill, decode-scan dispatch, COW copies, checkpoint stalls — not the
device profile (that is what `jax.profiler` and the POST /profile hook
capture). This tracer records nestable wall-clock spans into a bounded
ring and exports them as Chrome trace-event JSON (the `{"traceEvents":
[...]}` form), loadable in Perfetto / chrome://tracing.

Correlation model (docs/GUIDE.md "Observability"): every span carries
its emitter's args — engine spans the request id (`rid`) and round
number, trainer spans the train step — so a client-visible stall greps
from the SSE `id:` field to the exact engine rounds it spanned, and a
loss spike to the data-fetch/step/save spans around it.

The HARD contract (pinned by tests/test_telemetry.py and the
graft-check audit): emission is pure host bookkeeping — perf_counter
reads, dict literals, deque appends. No tracer method may touch a jax
value, so telemetry-on jitted steps are bitwise-identical to
telemetry-off by construction, and `analysis/lint.py` lists the emit
methods in GR006 HOT_PATHS so a device sync can never creep in.

A disabled tracer (`enabled=False`, the default everywhere no
--trace_dir is given) short-circuits every emitter to a shared no-op
span: the off cost is one attribute check per site.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

__all__ = ["SpanTracer", "NULL_TRACER"]


class _NullSpan:
    """Shared no-op context manager: the telemetry-off fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records a complete ("ph": "X") event on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._t0 = time.perf_counter()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # pure host bookkeeping (GR006 HOT_PATHS): one clock read and
        # one ring append — never a device value
        self._tracer.complete(self._name, self._t0, time.perf_counter(),
                              **self._args)
        return False


class SpanTracer:
    """Bounded ring of Chrome trace events with nestable span emitters.

    Nesting is positional, the Chrome trace-event way: a span emitted
    while another is open on the same thread lies inside it on the
    timeline (child `ts`/`ts+dur` contained in the parent's), so no
    explicit parent pointers are kept — the emit path stays O(1).

    `set_context(**kv)` attaches ambient correlation keys (e.g. the
    trainer's current `step`) merged into every subsequent event's args;
    per-call args win on collision.
    """

    def __init__(self, capacity: int = 65536, enabled: bool = True):
        self.enabled = enabled
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        # serializes ring mutation vs events()/export(): iterating the
        # deque while another thread appends raises RuntimeError (the
        # HTTP/bench threads read while the serve loop emits)
        self._events_lock = threading.Lock()
        self._epoch = time.perf_counter()
        self._epoch_unix = time.time()
        self._context: dict = {}
        self._pid = os.getpid()
        # stable small tids: Perfetto tracks read better as "tid 1..n"
        # than 140737352472320
        self._tids: dict = {}
        self._tid_lock = threading.Lock()
        self.dropped = 0  # events pushed past capacity (ring overwrote)

    # -- emitters (GR006 HOT_PATHS: host bookkeeping only) -----------------

    def span(self, name: str, **args):
        """Context manager measuring one complete span."""
        if not self.enabled:
            return _NULL_SPAN
        if self._context:
            args = {**self._context, **args}
        return _Span(self, name, args)

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker event (ph "i")."""
        if not self.enabled:
            return
        if self._context:
            args = {**self._context, **args}
        self._push({"name": name, "ph": "i", "s": "t",
                    "ts": self._ts(time.perf_counter()),
                    "pid": self._pid, "tid": self._tid(), "args": args})

    def complete(self, name: str, t0: float, t1: float, **args) -> None:
        """Record a complete span from two perf_counter readings — the
        retroactive form: the engine books `queue_wait` at admission
        from the request's own submit/admit stamps, after the fact."""
        if not self.enabled:
            return
        if self._context:
            args = {**self._context, **args}
        self._push({"name": name, "ph": "X", "ts": self._ts(t0),
                    "dur": max(round((t1 - t0) * 1e6), 0),
                    "pid": self._pid, "tid": self._tid(), "args": args})

    def set_context(self, **kv) -> None:
        """Merge ambient correlation keys into subsequent events' args
        (e.g. `set_context(step=it)` each trainer iteration). No-op
        when disabled: NULL_TRACER is a shared module singleton, and
        every telemetry-off component calls this per step — mutating
        one global dict from all of them would be cross-component
        state for nothing."""
        if not self.enabled:
            return
        self._context.update(kv)

    # -- internals ---------------------------------------------------------

    def _ts(self, t: float) -> int:
        return round((t - self._epoch) * 1e6)  # us since tracer epoch

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._tid_lock:
                tid = self._tids.setdefault(ident, len(self._tids) + 1)
        return tid

    def _push(self, ev: dict) -> None:
        with self._events_lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(ev)

    # -- export ------------------------------------------------------------

    def events(self) -> list:
        """Snapshot of the ring, sorted by ts (deque appends from
        concurrent threads may interleave slightly out of order; the
        trace-event format wants monotone ts)."""
        with self._events_lock:
            evs = list(self._events)
        return sorted(evs, key=lambda e: (e["pid"], e["tid"], e["ts"]))

    def to_chrome_trace(self) -> dict:
        """The Chrome trace-event JSON object Perfetto loads."""
        evs = self.events()
        # thread-name metadata events so Perfetto labels the tracks
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": "megatron_llm_tpu"}}]
        for ident, tid in sorted(self._tids.items(), key=lambda x: x[1]):
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self._pid, "tid": tid,
                         "args": {"name": f"host-thread-{tid}"}})
        return {
            "traceEvents": meta + evs,
            "displayTimeUnit": "ms",
            "otherData": {
                "epoch_unix": self._epoch_unix,
                "dropped_events": self.dropped,
            },
        }

    def export(self, path: str) -> Optional[str]:
        """Write the Chrome trace JSON artifact; returns the path (None
        when the tracer is disabled — nothing to write)."""
        if not self.enabled:
            return None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self.to_chrome_trace(), fh)
        os.replace(tmp, path)
        return path


# the shared disabled tracer: every component's default when no
# --trace_dir is configured (one attribute check per emit site)
NULL_TRACER = SpanTracer(capacity=1, enabled=False)
