"""Flash attention — the flagship Pallas kernel of the build. Fwd + bwd.

Replaces the reference's external FlashAttention-2 dependency
(ref: requirements.txt:3, transformer.py:508-523 — the reference TRAINS
through flash-attn, so the backward here is load-bearing) and the three
fused softmax CUDA kernels (ref: megatron/fused_kernels/scaled_*softmax*).

GQA/MQA-aware: K/V stay at `num_query_groups` heads and are never
broadcast-expanded (the reference expands them, transformer.py:449-456).
Layout: q (b, s, g, qpk, d), k/v (b, t, g, d) — the grouped layout used
throughout megatron_llm_tpu.models.attention. Inside the kernels the
(position, q-head) pair is folded into one row dim (head fastest), so one
MXU matmul serves all q heads of a group.

Backward follows the FlashAttention-2 recomputation scheme: the forward
saves only O and the per-row logsumexp; the backward recomputes the score
blocks and accumulates dq (grid over q blocks) and dk/dv (grid over k
blocks) in fp32 VMEM scratch, with delta = rowsum(dO * O) precomputed.

`flash_attention` dispatches to the Pallas kernels on TPU and to a
numerically identical XLA fallback elsewhere; `interpret=True` runs the
real kernels through the Pallas interpreter (used by the CPU test suite).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from megatron_llm_tpu.analysis.contracts import (
    CompileContract,
    register_contract,
)

register_contract(CompileContract(
    name="ops.flash_attention",
    max_variants=None,  # traced per (shape, statics) by jax's jit
    # cache; the model's fixed (b, s, heads, d) keeps the key space to
    # the handful of layouts a config actually runs
    collectives={"single": frozenset()},
    tmp_bytes_budget=2 << 20,  # 32 KB measured at the audit config
    notes="audited on the dense XLA path (use_pallas=False): the "
          "Pallas kernel is TPU-gated and interpret mode IS a host "
          "callback by construction"))

NEG_INF = -1e30
# The kernels run the online softmax in the exp2 domain (scores pre-scaled
# by log2(e)): the TPU transcendental unit computes exp2 natively, so
# exp(x) = exp2(x * log2e) folds one multiply per score cell into the GEMM
# scale. lse crosses the kernel boundary in NATURAL log units.
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453

# swept on a real v5e (r4, b6/g16/d128 @ seq 4096 and b8 @ 1024):
# 1024/1024 beats 512/1024 by ~10-12% fwd+bwd at both lengths (and
# 256/256 by >2x); _choose_block still shrinks for short sequences and
# many-q-per-kv GQA groups (MAX_ROWS cap), MAX_CELLS bounds VMEM
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
# cap on folded (position, head) rows per program so fp32 score blocks
# (rows x block_k) and the accumulators fit VMEM (~16 MB)
MAX_ROWS = 2048
# cap on rows*block_k fp32 score cells per program (4 MB per buffer; the
# backward holds two such blocks) — keeps wide-GQA shapes inside VMEM now
# that the default block_k is 1024
MAX_CELLS = 1 << 20


def _compiler_params(**kw):
    """pltpu.CompilerParams under current JAX; TPUCompilerParams on the
    0.4.x line — both accept dimension_semantics."""
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


def _xla_reference(q, k, v, causal: bool):
    """Un-tiled reference path; same math, XLA-fused softmax."""
    b, s, g, qpk, d = q.shape
    t = k.shape[1]
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    scores = jnp.einsum("bsgqd,btgd->bgqst", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        rows = jnp.arange(s)[:, None]
        cols = jnp.arange(t)[None, :]
        scores = jnp.where(cols > rows, jnp.finfo(jnp.float32).min, scores)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bgqst,btgd->bsgqd", probs, v)


def _xla_reference_with_lse(q, k, v, causal: bool):
    """Reference path that also returns the per-row logsumexp
    (b, s, g, qpk) fp32 — differentiable through BOTH outputs (autodiff;
    the merge-across-blocks users need d/dlse)."""
    b, s, g, qpk, d = q.shape
    t = k.shape[1]
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    scores = jnp.einsum("bsgqd,btgd->bgqst", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        rows = jnp.arange(s)[:, None]
        cols = jnp.arange(t)[None, :]
        scores = jnp.where(cols > rows, NEG_INF, scores)
    lse = jax.scipy.special.logsumexp(scores, axis=-1)  # (b, g, qpk, s)
    probs = jnp.exp(scores - lse[..., None]).astype(v.dtype)
    o = jnp.einsum("bgqst,btgd->bsgqd", probs, v)
    return o, jnp.moveaxis(lse, 3, 1)  # lse -> (b, s, g, qpk)


def _out_struct(shape, dtype, *likes):
    """ShapeDtypeStruct carrying the union of the operands' varying-
    manual-axes sets: inside a shard_map manual region (ring attention's
    per-hop call, the pipelined decode's stage region) the kernel
    outputs must declare how they vary across the manual axes or tracing
    rejects them (check_vma). On JAX builds without jax.typeof there are
    no manual regions to satisfy."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    vma = set()
    for x in likes:
        vma |= set(getattr(typeof(x), "vma", None) or ())
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def _choose_block(size: int, requested: int, qpk: int = 1):
    """Largest power-of-2 block <= requested that divides `size` and keeps
    folded rows (block*qpk) under MAX_ROWS. None if nothing fits (caller
    falls back to the XLA path). Power-of-2 keeps Mosaic tile alignment
    (sublane multiples of 8/16)."""
    b = 1 << (min(requested, size).bit_length() - 1)  # round down to pow2
    while b >= 8 and (size % b or b * qpk > MAX_ROWS):
        b //= 2
    return b if b >= 8 and size % b == 0 else None


# ---------------------------------------------------------------------------
# Shared attention-kernel template (ISSUE 18): the mask / online-softmax /
# fp32-accumulator core that every attention kernel in ops/ instantiates.
# The flash forward (dense training), the dense decode kernel, and the
# unified ragged paged kernel (ops/prefill_attention.py) all run their
# reduction through these helpers, so the exp2-domain running-(m, l, acc)
# scheme and the mask predicate are each ONE definition. The mask is a
# pluggable SHAPE: `_causal_invalid` is the causal family — dense causal
# (pos_base = q-block start), decode row (pos_base = cache offset), and
# ragged chunk (pos_base = slot start + block start, plus the pad-row
# bound `valid_rows`) are all parameterizations of one predicate; a
# sliding-window or packed-doc mask slots in as a new predicate function
# without touching any kernel body.
# ---------------------------------------------------------------------------


def _causal_invalid(rows, block_k, qpk, pos_base, col_base,
                    valid_rows=None, window=None, floor=None):
    """(rows, block_k) bool block, True = masked out. Folded row r (head
    fastest) is token r // qpk at causal position pos_base + r // qpk;
    column c is cache position col_base + c. With `valid_rows` (the
    ragged-chunk pad bound), rows at tokens >= valid_rows mask EVERY
    column. pos_base / valid_rows may be traced scalars.

    The two lower-bound parameterizations (ISSUE 19) are additive
    predicates on the same block, None = off (the trace is then
    bitwise the pre-window one):
    - `window` (static int >= 1): sliding-window attention — a row at
      position p attends only cols in [p - window + 1, p], so the
      window >= context case compares against bounds that never bind
      and stays bitwise-dense.
    - `floor` (traced scalar): packed-doc reset — every row of the
      block additionally masks cols < floor (the chunk's document
      start). Callers must keep floor <= the first row's own position
      or a valid row could mask every column (the finite-NEG_INF
      degenerate case only pad rows are re-masked for)."""
    tok = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 0) // qpk
    col = col_base + jax.lax.broadcasted_iota(
        jnp.int32, (rows, block_k), 1
    )
    invalid = col > pos_base + tok
    if window is not None:
        invalid = invalid | (col < pos_base + tok - (window - 1))
    if floor is not None:
        invalid = invalid | (col < floor)
    if valid_rows is not None:
        invalid = invalid | (tok >= valid_rows)
    return invalid


def _softmax_init(m_scr, l_scr, acc_scr):
    """Reset the running (max, sum, acc) VMEM scratch at the first
    reduction step of a grid row."""
    m_scr[:] = jnp.full_like(m_scr, NEG_INF)
    l_scr[:] = jnp.zeros_like(l_scr)
    acc_scr[:] = jnp.zeros_like(acc_scr)


def _softmax_accum(sc, vb, m_scr, l_scr, acc_scr, p_dtype=None):
    """One exp2-domain online-softmax step: fold the (rows, block_k)
    score block `sc` and its value block `vb` into the running fp32
    (m, l, acc) scratch. `p_dtype` casts the probabilities before the PV
    matmul (the fp kernels feed the MXU in the value dtype); the
    int8-dequant epilogue passes None and keeps fp32 — its vb was
    already dequantized in-register."""
    m_prev = m_scr[:]  # (rows, 1)
    m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
    alpha = jnp.exp2(m_prev - m_new)
    p = jnp.exp2(sc - m_new)  # (rows, block_k)
    l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
    if p_dtype is not None:
        p = p.astype(p_dtype)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
        p, vb, preferred_element_type=jnp.float32,
    )
    m_scr[:] = m_new


def _softmax_finalize(l_scr, acc_scr):
    """Close the reduction: returns (acc / max(l, eps), l) in fp32. The
    eps floor keeps all-masked rows finite; callers re-mask such rows to
    their exact-zero contract where one exists."""
    l = jnp.maximum(l_scr[:], 1e-30)
    return acc_scr[:] / l, l


def _masked_scores(q_ref, k_ref, i, j, *, masked, block_q, block_k, qpk, d,
                   sm_scale):
    """Recompute the scaled score block in the exp2 domain — the ONE
    definition shared by the forward and both backward kernels so fwd
    probabilities and bwd recompute can never desynchronize. `masked` is a
    TRACE-TIME flag: callers split their grid step into interior
    (fully-below-diagonal, no iota/select work) and diagonal-straddling
    branches, so the causal mask costs VPU time only on the ~1/num_blocks
    of blocks that actually straddle the diagonal.
    Returns (rows, block_k) fp32, scaled by sm_scale * log2(e)."""
    rows = block_q * qpk
    qb = q_ref[:].reshape(rows, d)
    kb = k_ref[:].reshape(block_k, d)
    sc = jax.lax.dot_general(
        qb, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (sm_scale * LOG2E)
    if masked:
        sc = jnp.where(
            _causal_invalid(rows, block_k, qpk, i * block_q, j * block_k),
            NEG_INF, sc,
        )
    return sc


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------
# Online-softmax tiling: grid over (batch*group, q_block, k_block); running
# (max, sum, acc) in fp32 VMEM scratch; emits O and the logsumexp rows.


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, causal, block_q, block_k, qpk, d, num_k_blocks, sm_scale,
                split_diag=True):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        _softmax_init(m_scr, l_scr, acc_scr)

    def _accum(masked):
        # rows: (pos, head), head fastest; running stats in exp2 domain
        sc = _masked_scores(
            q_ref, k_ref, i, j, masked=masked, block_q=block_q,
            block_k=block_k, qpk=qpk, d=d, sm_scale=sm_scale,
        )
        _softmax_accum(sc, v_ref[:].reshape(block_k, d), m_scr, l_scr,
                       acc_scr, p_dtype=v_ref.dtype)

    if causal:
        # skip fully-masked K blocks (k block start > last q position);
        # apply the mask only on diagonal-straddling blocks — interior
        # blocks (last col <= first q row) run the maskless branch.
        # (split_diag=False under the interpreter: the two-branch grid
        # step trips a vma check in the Pallas HLO interpreter.)
        run = (j * block_k) <= (i * block_q + block_q - 1)
        if split_diag:
            interior = (j * block_k + block_k - 1) <= (i * block_q)

            @pl.when(run & interior)
            def _compute_interior():
                _accum(False)

            @pl.when(run & ~interior)
            def _compute_diagonal():
                _accum(True)
        else:
            @pl.when(run)
            def _compute():
                _accum(True)
    else:
        @pl.when(j >= 0)  # always true; pl.when so the interpreter's vma
        def _compute():   # unification wraps the body (interpret mode)
            _accum(False)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        out, l = _softmax_finalize(l_scr, acc_scr)
        o_ref[:] = out.astype(o_ref.dtype).reshape(
            1, block_q, qpk * d
        )
        # rows-major (rows, 1) layout: Mosaic can't shape-cast the lane dim
        # into sublanes, so lse lives as (bg, s*qpk, 1) end to end.
        # m is in exp2 units; emit NATURAL-log lse (the kernel ABI).
        lse_ref[0] = m_scr[:] * LN2 + jnp.log(l)


def _flash_fwd_pallas(q, k, v, causal, block_q, block_k, interpret=False):
    """q: (b, s, g, qpk, d); k,v: (b, t, g, d).
    Returns (o (b,s,g,qpk,d), lse (b*g, s*qpk, 1) fp32 rows-major)."""
    b, s, g, qpk, d = q.shape
    t = k.shape[1]
    sm_scale = 1.0 / (d ** 0.5)
    assert s % block_q == 0 and t % block_k == 0

    qf = q.transpose(0, 2, 1, 3, 4).reshape(b * g, s, qpk * d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * g, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * g, t, d)

    num_q_blocks = s // block_q
    num_k_blocks = t // block_k

    kernel = functools.partial(
        _fwd_kernel, causal=causal, block_q=block_q, block_k=block_k,
        qpk=qpk, d=d, num_k_blocks=num_k_blocks, sm_scale=sm_scale,
        split_diag=not interpret,
    )
    grid = (b * g, num_q_blocks, num_k_blocks)

    if causal:
        # skipped above-diagonal blocks clamp their K/V index to the last
        # allowed block: Mosaic detects the repeated block index and skips
        # the DMA, so masked grid steps cost no HBM traffic
        def kv_index(h, i, j):
            return (h, jnp.minimum(j, (i * block_q + block_q - 1)
                                   // block_k), 0)
    else:
        def kv_index(h, i, j):
            return (h, j, 0)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, qpk * d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, qpk * d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, block_q * qpk, 1), lambda h, i, j: (h, i, 0)),
        ],
        out_shape=[
            _out_struct((b * g, s, qpk * d), q.dtype, qf),
            _out_struct((b * g, s * qpk, 1), jnp.float32, qf),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q * qpk, 1), jnp.float32),
            pltpu.VMEM((block_q * qpk, 1), jnp.float32),
            pltpu.VMEM((block_q * qpk, d), jnp.float32),
        ],
        # (bg, q) grid steps are independent; only the k dim carries the
        # online-softmax accumulator state
        compiler_params=None if interpret else _compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, g, s, qpk, d).transpose(0, 2, 1, 3, 4), lse


# ---------------------------------------------------------------------------
# Backward kernels (FlashAttention-2 recomputation scheme)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_scr, *, causal, block_q, block_k, qpk, d,
                   num_k_blocks, sm_scale, split_diag=True):
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _accum(masked):
        rows = block_q * qpk
        kb = k_ref[:].reshape(block_k, d)
        vb = v_ref[:].reshape(block_k, d)
        dob = do_ref[:].reshape(rows, d)

        sc = _masked_scores(
            q_ref, k_ref, i, j, masked=masked, block_q=block_q,
            block_k=block_k, qpk=qpk, d=d, sm_scale=sm_scale,
        )
        # exact probs via saved logsumexp; sc is exp2-domain, the saved
        # lse is natural-log — rescale the (rows, 1) vector, not the block
        p = jnp.exp2(sc - lse_ref[0] * LOG2E)
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        acc_scr[:] = acc_scr[:] + jax.lax.dot(
            ds.astype(kb.dtype), kb, preferred_element_type=jnp.float32
        )

    if causal:
        run = (j * block_k) <= (i * block_q + block_q - 1)
        if split_diag:
            interior = (j * block_k + block_k - 1) <= (i * block_q)

            @pl.when(run & interior)
            def _compute_interior():
                _accum(False)

            @pl.when(run & ~interior)
            def _compute_diagonal():
                _accum(True)
        else:
            @pl.when(run)
            def _compute():
                _accum(True)
    else:
        @pl.when(j >= 0)
        def _compute():
            _accum(False)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        dq_ref[:] = (acc_scr[:] * sm_scale).astype(dq_ref.dtype).reshape(
            1, block_q, qpk * d
        )


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, causal, block_q,
                    block_k, qpk, d, num_q_blocks, sm_scale,
                    split_diag=True):
    j = pl.program_id(1)  # k block
    i = pl.program_id(2)  # q block

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _accum(masked):
        rows = block_q * qpk
        qb = q_ref[:].reshape(rows, d)
        vb = v_ref[:].reshape(block_k, d)
        dob = do_ref[:].reshape(rows, d)

        sc = _masked_scores(
            q_ref, k_ref, i, j, masked=masked, block_q=block_q,
            block_k=block_k, qpk=qpk, d=d, sm_scale=sm_scale,
        )
        p = jnp.exp2(sc - lse_ref[0] * LOG2E)  # (rows, block_k)
        # dv += P^T dO
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        # dk += dS^T Q
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # q blocks strictly before this k block contribute nothing
        run = (i * block_q + block_q - 1) >= (j * block_k)
        if split_diag:
            interior = (j * block_k + block_k - 1) <= (i * block_q)

            @pl.when(run & interior)
            def _compute_interior():
                _accum(False)

            @pl.when(run & ~interior)
            def _compute_diagonal():
                _accum(True)
        else:
            @pl.when(run)
            def _compute():
                _accum(True)
    else:
        @pl.when(i >= 0)
        def _compute():
            _accum(False)

    @pl.when(i == num_q_blocks - 1)
    def _finalize():
        dk_ref[:] = (dk_scr[:] * sm_scale).astype(dk_ref.dtype).reshape(
            1, block_k, d
        )
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype).reshape(1, block_k, d)


def _flash_bwd_pallas(q, k, v, o, lse, do, causal, block_q, block_k,
                      interpret=False, dlse_rows=None):
    b, s, g, qpk, d = q.shape
    t = k.shape[1]
    sm_scale = 1.0 / (d ** 0.5)

    qf = q.transpose(0, 2, 1, 3, 4).reshape(b * g, s, qpk * d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * g, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * g, t, d)
    dof = do.transpose(0, 2, 1, 3, 4).reshape(b * g, s, qpk * d)
    # delta = rowsum(dO * O) — one fused elementwise reduce, XLA does this
    # as well as a kernel would (ref FA2 preprocess step); rows-major layout
    # matching lse
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1, 3).reshape(b * g, s * qpk, 1)
    if dlse_rows is not None:
        # lse as a primal OUTPUT: d lse / d score_ij = p_ij, so the score
        # cotangent gains + g_lse * p — exactly ds = p*(dp - (delta -
        # g_lse)); folding it into delta costs nothing in-kernel
        delta = delta - dlse_rows

    num_q_blocks = s // block_q
    num_k_blocks = t // block_k

    # causal DMA clamps (see _flash_fwd_pallas): masked grid steps re-fetch
    # the previous block index, which Mosaic elides
    if causal:
        def kv_index(h, i, j):
            return (h, jnp.minimum(j, (i * block_q + block_q - 1)
                                   // block_k), 0)

        def q_index_t(h, j, i):
            return (h, jnp.maximum(i, (j * block_k) // block_q), 0)
    else:
        def kv_index(h, i, j):
            return (h, j, 0)

        def q_index_t(h, j, i):
            return (h, i, 0)

    row_specs = [
        pl.BlockSpec((1, block_q, qpk * d), lambda h, i, j: (h, i, 0)),  # q
        pl.BlockSpec((1, block_k, d), kv_index),                         # k
        pl.BlockSpec((1, block_k, d), kv_index),                         # v
        pl.BlockSpec((1, block_q, qpk * d), lambda h, i, j: (h, i, 0)),  # do
        pl.BlockSpec((1, block_q * qpk, 1), lambda h, i, j: (h, i, 0)),  # lse
        pl.BlockSpec((1, block_q * qpk, 1), lambda h, i, j: (h, i, 0)),  # delta
    ]
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, block_q=block_q, block_k=block_k,
            qpk=qpk, d=d, num_k_blocks=num_k_blocks, sm_scale=sm_scale,
            split_diag=not interpret,
        ),
        grid=(b * g, num_q_blocks, num_k_blocks),
        in_specs=row_specs,
        out_specs=pl.BlockSpec((1, block_q, qpk * d), lambda h, i, j: (h, i, 0)),
        out_shape=_out_struct((b * g, s, qpk * d), q.dtype, qf),
        scratch_shapes=[pltpu.VMEM((block_q * qpk, d), jnp.float32)],
        compiler_params=None if interpret else _compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    col_specs = [
        pl.BlockSpec((1, block_q, qpk * d), q_index_t),                  # q
        pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0)),        # k
        pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0)),        # v
        pl.BlockSpec((1, block_q, qpk * d), q_index_t),                  # do
        pl.BlockSpec((1, block_q * qpk, 1), q_index_t),                  # lse
        pl.BlockSpec((1, block_q * qpk, 1), q_index_t),                  # delta
    ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, block_q=block_q, block_k=block_k,
            qpk=qpk, d=d, num_q_blocks=num_q_blocks, sm_scale=sm_scale,
            split_diag=not interpret,
        ),
        grid=(b * g, num_k_blocks, num_q_blocks),
        in_specs=col_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0)),
        ],
        out_shape=[
            _out_struct((b * g, t, d), k.dtype, qf),
            _out_struct((b * g, t, d), v.dtype, qf),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=None if interpret else _compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dq = dq.reshape(b, g, s, qpk, d).transpose(0, 2, 1, 3, 4)
    dk = dk.reshape(b, g, t, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, g, t, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper (ref parity: training THROUGH flash attention,
# transformer.py:508-523)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(config, q, k, v):
    causal, block_q, block_k, interpret = config
    o, _ = _flash_fwd_pallas(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_fwd_rule(config, q, k, v):
    causal, block_q, block_k, interpret = config
    o, lse = _flash_fwd_pallas(q, k, v, causal, block_q, block_k, interpret)
    # Named save points on the residuals (models/remat.py): when an outer
    # jax.checkpoint runs a selective/offload policy, keeping o AND the
    # (tiny, b*s*heads fp32) lse rows means the backward consumes the
    # saved residuals directly — the forward kernel is never re-run; only
    # the bwd kernels (which recompute scores tile-by-tile) execute.
    from jax.ad_checkpoint import checkpoint_name

    return o, (q, k, v, checkpoint_name(o, "attn_ctx"),
               checkpoint_name(lse, "flash_lse"))


def _flash_bwd_rule(config, residuals, g):
    causal, block_q, block_k, interpret = config
    q, k, v, o, lse = residuals
    return _flash_bwd_pallas(
        q, k, v, o, lse, g, causal, block_q, block_k, interpret
    )


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _lse_rows_to_bsgq(lse_rows, b, s, g, qpk):
    # (b*g, s*qpk, 1) rows-major (head fastest) -> (b, s, g, qpk)
    return lse_rows.reshape(b, g, s, qpk).transpose(0, 2, 1, 3)


def _lse_bsgq_to_rows(lse, b, s, g, qpk):
    return lse.transpose(0, 2, 1, 3).reshape(b * g, s * qpk, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_lse(config, q, k, v):
    causal, block_q, block_k, interpret = config
    b, s, g, qpk, _ = q.shape
    o, lse = _flash_fwd_pallas(q, k, v, causal, block_q, block_k, interpret)
    return o, _lse_rows_to_bsgq(lse, b, s, g, qpk)


def _flash_lse_fwd_rule(config, q, k, v):
    causal, block_q, block_k, interpret = config
    b, s, g, qpk, _ = q.shape
    o, lse = _flash_fwd_pallas(q, k, v, causal, block_q, block_k, interpret)
    return (o, _lse_rows_to_bsgq(lse, b, s, g, qpk)), (q, k, v, o, lse)


def _flash_lse_bwd_rule(config, residuals, cts):
    causal, block_q, block_k, interpret = config
    q, k, v, o, lse = residuals
    do, dlse = cts
    b, s, g, qpk, _ = q.shape
    dlse_rows = _lse_bsgq_to_rows(dlse.astype(jnp.float32), b, s, g, qpk)
    return _flash_bwd_pallas(
        q, k, v, o, lse, do, causal, block_q, block_k, interpret,
        dlse_rows=dlse_rows,
    )


_flash_lse.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)


def flash_attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    use_pallas: bool | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
):
    """Like `flash_attention` but ALSO returns the per-row logsumexp
    (b, s, g, qpk) fp32, differentiable through both outputs — the
    building block for merging attention across blocks that live on
    different devices (ring attention's per-hop step)."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        blocks = _pick_blocks(q.shape[1], k.shape[1], q.shape[-1],
                              q.shape[3], block_q, block_k)
        if blocks is not None:
            return _flash_lse((causal, *blocks, interpret), q, k, v)
    return _xla_reference_with_lse(q, k, v, causal)


def _pick_blocks(s, t, d, qpk, block_q, block_k):
    """Shared block selection for both entry points: shrink to divisors,
    bound the fp32 score block rows*block_k under VMEM (MAX_CELLS), gate
    on lane alignment. Returns (bq, bk) or None for the XLA fallback."""
    bq = _choose_block(s, block_q, qpk)
    bk = _choose_block(t, block_k)
    while (bq is not None and bk is not None and bk > 128
           and bq * qpk * bk > MAX_CELLS):
        bk = _choose_block(t, bk // 2)
    while (bq is not None and bk is not None
           and bq * qpk * bk > MAX_CELLS and bq * qpk > 256):
        bq = _choose_block(s, bq // 2, qpk)
    if bq is None or bk is None or d % 128 != 0:
        return None
    return bq, bk


# graft-contract: ops.flash_attention
@functools.partial(jax.jit, static_argnames=("causal", "use_pallas",
                                             "block_q", "block_k",
                                             "interpret"))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    use_pallas: bool | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jnp.ndarray:
    """GQA flash attention, differentiable. Returns (b, s, g, qpk, d).

    The output is tagged as the "attn_ctx" named save point (and the
    custom-VJP residuals tag o/lse, see _flash_fwd_rule) so the
    named-savepoint remat policies (models/remat.py) can keep it."""
    from jax.ad_checkpoint import checkpoint_name

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        blocks = _pick_blocks(q.shape[1], k.shape[1], q.shape[-1],
                              q.shape[3], block_q, block_k)
        if blocks is not None:
            return checkpoint_name(
                _flash((causal, *blocks, interpret), q, k, v), "attn_ctx"
            )
    return checkpoint_name(_xla_reference(q, k, v, causal), "attn_ctx")
