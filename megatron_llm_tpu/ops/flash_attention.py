"""Flash attention — the flagship Pallas kernel of the build.

Replaces the reference's external FlashAttention-2 dependency
(ref: requirements.txt:3, transformer.py:508-523) and the three fused
softmax CUDA kernels (ref: megatron/fused_kernels/scaled_*softmax*). The
kernel is GQA/MQA-aware: K/V stay at `num_query_groups` heads and are never
broadcast-expanded (the reference expands them, transformer.py:449-456).

Layout: q (b, s, g, qpk, d), k/v (b, t, g, d) — the grouped layout used
throughout megatron_llm_tpu.models.attention.

`flash_attention` dispatches to the Pallas kernel on TPU and to a
numerically identical XLA fallback elsewhere (CPU tests, interpret mode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _xla_reference(q, k, v, causal: bool):
    """Un-tiled reference path; same math, XLA-fused softmax."""
    b, s, g, qpk, d = q.shape
    t = k.shape[1]
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    scores = jnp.einsum("bsgqd,btgd->bgqst", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        rows = jnp.arange(s)[:, None]
        cols = jnp.arange(t)[None, :]
        scores = jnp.where(cols > rows, jnp.finfo(jnp.float32).min, scores)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bgqst,btgd->bsgqd", probs, v)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------
# Online-softmax tiling: grid over (batch*group, q_block); each program
# streams K/V blocks with running (max, sum, acc) in fp32 VMEM scratch.

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _flash_fwd_pallas(q, k, v, causal: bool, block_q: int, block_k: int):
    """q: (b, s, g, qpk, d); k,v: (b, t, g, d)."""
    b, s, g, qpk, d = q.shape
    t = k.shape[1]
    sm_scale = 1.0 / (d ** 0.5)
    block_q = min(block_q, s)
    block_k = min(block_k, t)
    assert s % block_q == 0 and t % block_k == 0

    # (b*g, s, qpk, d) -> (bg, s*qpk rows? ) — keep (bg, s, qpk, d); fold qpk
    # into the row dim per q-block inside the kernel via reshape.
    qf = q.transpose(0, 2, 1, 3, 4).reshape(b * g, s, qpk * d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * g, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * g, t, d)

    num_q_blocks = s // block_q
    num_k_blocks = t // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr):
        i = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, -1e30)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        if causal:
            # skip fully-masked K blocks (k block start > last q position)
            run = (j * block_k) <= (i * block_q + block_q - 1)
        else:
            run = j >= 0  # always true, but traced

        @pl.when(run)
        def _compute():
            qb = q_ref[:].reshape(block_q * qpk, d)  # rows: (pos, head), head fastest
            kb = k_ref[:].reshape(block_k, d)
            sc = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale  # (rows, block_k)

            if causal:
                q_pos = i * block_q + (
                    jax.lax.broadcasted_iota(jnp.int32, (block_q * qpk, block_k), 0)
                    // qpk
                )
                k_pos = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q * qpk, block_k), 1
                )
                sc = jnp.where(k_pos > q_pos, -1e30, sc)

            m_prev = m_scr[:]  # (rows, 1)
            m_cur = jnp.max(sc, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(sc - m_new)  # (rows, block_k)
            l_new = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
                p.astype(v_ref.dtype), v_ref[:].reshape(block_k, d),
                preferred_element_type=jnp.float32,
            )
            m_scr[:] = m_new
            l_scr[:] = l_new

        @pl.when(j == num_k_blocks - 1)
        def _finalize():
            o_ref[:] = (
                acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)
            ).astype(o_ref.dtype).reshape(1, block_q, qpk * d)

    grid = (b * g, num_q_blocks, num_k_blocks)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, qpk * d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, qpk * d), lambda h, i, j: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * g, s, qpk * d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q * qpk, 1), jnp.float32),
            pltpu.VMEM((block_q * qpk, 1), jnp.float32),
            pltpu.VMEM((block_q * qpk, d), jnp.float32),
        ],
    )(qf, kf, vf)
    return out.reshape(b, g, s, qpk, d).transpose(0, 2, 1, 3, 4)


@functools.partial(jax.jit, static_argnames=("causal", "use_pallas",
                                             "block_q", "block_k"))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    use_pallas: bool | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jnp.ndarray:
    """GQA flash attention. Returns (b, s, g, qpk, d)."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        s, t, d = q.shape[1], k.shape[1], q.shape[-1]
        bq = min(block_q, s)
        bk = min(block_k, t)
        if s % bq == 0 and t % bk == 0 and d % 128 == 0:
            return _flash_fwd_pallas(q, k, v, causal, bq, bk)
    return _xla_reference(q, k, v, causal)
