"""Fused RMSNorm — Pallas kernel, fwd + bwd. NOT the default path.

Parity target: ref megatron/model/fused_layer_norm.py:64-139 — the
reference routes RMSNorm/LayerNorm through apex's fused CUDA kernels
because torch eager would otherwise issue multiple kernels. XLA already
fuses the whole RMSNorm into its neighbors, so the honest status of this
kernel (measured in-jit on a v5e, r4, scan-amortized so no dispatch
overhead): ~PAR with the XLA path — (rows=12k, h=2048) fwd 3.06ms vs
XLA 2.33ms, (rows=24k) fwd 3.36ms vs 3.49ms / fwd+bwd 2.6ms vs 4.1ms.
It is kept as the Pallas-toolchain reference + test vector and an
opt-in (cfg.use_fused_rmsnorm / `use_pallas=True`), NOT wired as a
default: on TPU there is no apex-shaped win to claim here, and
models/norms.py + XLA fusion is the production path.

One pass over HBM per direction: the forward reads x once, computes the
fp32 row statistic in VMEM and writes the normalized/scaled output plus
the per-row rstd; the backward recomputes x_hat from the saved rstd and
emits dx and a per-row-block partial of dscale (summed by XLA outside).

Math matches models/norms.rms_norm exactly, including the cast order
(normalize in fp32, cast to the input dtype, THEN apply the scale —
ref: fused_layer_norm.py:133-138).

`interpret=True` runs the real kernel through the Pallas interpreter
(CPU test suite).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_ROWS = 256
# The backward holds ~4 fp32 row blocks (x, g, u, x_hat) + 2 bf16 blocks
# live at once; block*h is capped so the worst case stays well under the
# 16MB VMEM scoped limit (512K floats -> ~10MB worst case).
_VMEM_BUDGET = 512 * 1024  # floats per block


def _choose_rows(n_rows: int, h: int) -> int | None:
    b = DEFAULT_BLOCK_ROWS
    while b >= 8 and (n_rows % b or b * h > _VMEM_BUDGET):
        b //= 2
    return b if b >= 8 and n_rows % b == 0 else None


def _fwd_kernel(x_ref, s_ref, o_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)  # (rows, h)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    normed = (x * rstd).astype(o_ref.dtype)
    o_ref[:] = normed * s_ref[:].astype(o_ref.dtype)
    rstd_ref[:] = rstd


def _bwd_kernel(x_ref, s_ref, rstd_ref, g_ref, dx_ref, ds_ref, *, h):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    s = s_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]  # (rows, 1) fp32
    x_hat = x * rstd
    u = g * s[None, :]
    # dx = rstd * (u - x_hat * mean(u * x_hat)) over the hidden axis
    corr = jnp.mean(u * x_hat, axis=-1, keepdims=True)
    dx_ref[:] = (rstd * (u - x_hat * corr)).astype(dx_ref.dtype)
    # dscale accumulator: the TPU grid is sequential and ds maps to the
    # same (8, h) block every step, so it stays resident in VMEM; each
    # step adds colsum/8 to all 8 sublanes (Mosaic requires >=8-row
    # blocks; /8 is exact in fp32), caller sums the rows back.
    colsum = jnp.sum(g * x_hat.astype(g_ref.dtype).astype(jnp.float32),
                     axis=0, keepdims=True)

    @pl.when(i == 0)
    def _init():
        ds_ref[:] = jnp.zeros_like(ds_ref)

    ds_ref[:] += jnp.broadcast_to(colsum / 8.0, ds_ref.shape)


def _pallas_fwd(x2, scale, eps, block_rows, interpret):
    n, h = x2.shape
    grid = (n // block_rows,)
    out, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, scale)
    return out, rstd


def _pallas_bwd(x2, scale, rstd, g2, block_rows, interpret):
    n, h = x2.shape
    grid = (n // block_rows,)
    dx, ds_part = pl.pallas_call(
        functools.partial(_bwd_kernel, h=h),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
            pl.BlockSpec((8, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            jax.ShapeDtypeStruct((8, h), jnp.float32),
        ],
        interpret=interpret,
    )(x2, scale, rstd, g2)
    return dx, ds_part.sum(0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _fused(x2, scale, eps, block_rows, interpret):
    out, _ = _pallas_fwd(x2, scale, eps, block_rows, interpret)
    return out


def _fused_fwd(x2, scale, eps, block_rows, interpret):
    out, rstd = _pallas_fwd(x2, scale, eps, block_rows, interpret)
    return out, (x2, scale, rstd)


def _fused_bwd(eps, block_rows, interpret, res, g):
    x2, scale, rstd = res
    dx, ds = _pallas_bwd(x2, scale, rstd, g, block_rows, interpret)
    return dx, ds.astype(scale.dtype)


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6,
                   use_pallas: bool | None = None,
                   interpret: bool = False) -> jnp.ndarray:
    """RMSNorm over the last axis; differentiable. Any leading shape."""
    from megatron_llm_tpu.models.norms import rms_norm

    h = x.shape[-1]
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas and h % 128 == 0:
        lead = x.shape[:-1]
        n = 1
        for d in lead:
            n *= d
        block_rows = _choose_rows(n, h)
        if block_rows is not None:
            out = _fused((x.reshape(n, h)), scale, eps, block_rows,
                         interpret)
            return out.reshape(*lead, h)
    return rms_norm(x, scale, eps)
