"""Pallas decode-attention kernel — batched KV-cached decode at line rate.

The decode hot loop (inference/generation.py while_loop body) attends ONE
query token per sequence against the growing K/V cache. XLA lowers the
single-token QK/PV contractions to multiply-reduce loops that stream the
cache far below HBM bandwidth (measured r5: b=8 decode at 4.7 ms/step vs a
~3 ms weights+cache streaming floor — VERDICT r5 weak #2). This kernel
streams the cache through VMEM the way ops/flash_attention.py streams K/V
blocks in training, with decode-specific structure:

- grid (batch, group, cache_block): one grid step reads each K/V block
  ONCE per GQA group and serves all `q_per_kv` query heads of the group
  from it (the (position, head) fold of the flash kernel, with s == 1);
- the mask / online-softmax / fp32-accumulator core is the shared
  template of ops/flash_attention.py (`_causal_invalid` +
  `_softmax_init/accum/finalize`, ISSUE 18) instantiated at the dense
  standalone-cache parameterization;
- the VALID cache length rides a scalar-prefetch operand: block index
  maps clamp past-the-end blocks to the last valid block (Mosaic elides
  the repeated DMA, so masked grid steps cost no HBM traffic — the cache
  reads scale with the CURRENT length, not the allocated buffer), and
  in-kernel iota masking covers the straddling block — no dense
  (s, T) mask is ever materialized;
- two cache layouts, matching the two decode engines:
  "gtd" (b, g, T, d) — the per-layer standalone caches of the unrolled
  decode path (models/gpt.py init_kv_caches(layout="layers"));
  "tgd" (b, T, g, d) — the per-layer slice of the stacked (L, b, T, g, d)
  caches the pipelined stage-ring decode carries (parallel/pipeline.py).
  Both are consumed in place; neither is transposed or copied.

`decode_attention` dispatches to the kernel on TPU (or under
`interpret=True` through the Pallas interpreter — the CPU test path) and
to `_xla_decode`, a numerically matching reference, elsewhere.
`decode_attn_block` is the static viability check the model layer gates
on; it returns the chosen cache block size or None (XLA fallback).

This module serves DENSE per-sequence caches only. The continuous-
batching engine's paged pool — every phase of it, decode rows included,
fp and int8 — is served by THE ragged paged attention kernel in
ops/prefill_attention.py (ISSUE 18 collapsed the former paged decode /
ragged prefill / int8-twin fork into that one kernel; a decode step is
its width-1 chunk). `_xla_decode` here is a layout shim over the shared
`_xla_attend` dense core of that module.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from megatron_llm_tpu.ops.flash_attention import (
    LOG2E,
    _causal_invalid,
    _compiler_params,
    _out_struct,
    _softmax_accum,
    _softmax_finalize,
    _softmax_init,
    NEG_INF,
)
from megatron_llm_tpu.ops.prefill_attention import _xla_attend

# swept space: 256 balances DMA amortization against the clamp granularity
# (past-the-end traffic is at most one block); _choose_block_t shrinks to
# the largest power-of-2 divisor of the allocated cache length.
DEFAULT_BLOCK_T = 256
# folded (position, head) rows per sequence-group — decode is s == 1 so
# this only bites exotic MQA configs (q_per_kv > 128)
MAX_DECODE_ROWS = 128


def _choose_block_t(T: int, requested: int = DEFAULT_BLOCK_T) -> Optional[int]:
    """Largest power-of-2 block <= requested dividing the allocated cache
    length T. Min 16 keeps bf16 sublane tiling; None -> XLA fallback."""
    b = 1 << (min(requested, T).bit_length() - 1)
    while b >= 16 and T % b:
        b //= 2
    return b if b >= 16 and T % b == 0 else None


def decode_attn_block(s: int, qpk: int, d: int, T: int, *,
                      min_cache: int = 0,
                      requested: int = DEFAULT_BLOCK_T,
                      interpret: bool = False) -> Optional[int]:
    """Static dispatch check for the decode kernel: returns the cache
    block size, or None when the XLA path should serve this shape.

    Kernel territory: single-token steps (s == 1 — prefill chunks keep
    the batched-GEMM path, which is compute- not bandwidth-bound), lane-
    aligned head_dim, an allocated cache at least `min_cache` long (below
    that the matvecs are too small for kernel launch overhead to pay),
    and a power-of-2 block dividing T. On CPU the kernel only runs under
    the interpreter (the test path); otherwise TPU-only, mirroring
    flash_attention's backend dispatch.
    """
    if not (interpret or jax.default_backend() == "tpu"):
        return None
    if s != 1 or s * qpk > MAX_DECODE_ROWS or d % 128 != 0:
        return None
    if T < max(min_cache, 16):
        return None
    return _choose_block_t(T, requested)


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr,
                   acc_scr, *, block_t, rows, qpk, d, num_t_blocks,
                   sm_scale, s, split_boundary=True):
    """Grid (b, g, num_t_blocks); the t dim carries the online-softmax
    state in VMEM scratch. Row r of the folded (rows, d) q block is query
    position offset + r // qpk (head fastest), offset = length - s. The
    shared flash template at the dense decode parameterization: causal
    predicate `col <= offset + row`, no pad rows (every row is a live
    query token)."""
    j = pl.program_id(2)
    length = len_ref[0]
    offset = length - s

    @pl.when(j == 0)
    def _init():
        _softmax_init(m_scr, l_scr, acc_scr)

    def _accum(masked):
        # fp32 QK on tiny row counts: decode is cache-bandwidth-bound, so
        # MXU precision costs nothing; scores live in the exp2 domain
        # (sm_scale folded with log2(e), flash kernel convention)
        qb = q_ref[:].reshape(rows, d)
        kb = k_ref[:].reshape(block_t, d).astype(jnp.float32)
        sc = jax.lax.dot_general(
            qb.astype(jnp.float32), kb,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (sm_scale * LOG2E)
        if masked:
            # causal-within-step + cache-length mask in one predicate:
            # col c valid for row r iff c <= offset + r//qpk
            sc = jnp.where(
                _causal_invalid(rows, block_t, qpk, offset, j * block_t),
                NEG_INF, sc,
            )
        _softmax_accum(sc, v_ref[:].reshape(block_t, d), m_scr, l_scr,
                       acc_scr, p_dtype=v_ref.dtype)

    # blocks entirely past the valid length skip compute (their DMA was
    # clamped to the last valid block by the index map); interior blocks
    # (fully <= offset, every row) run maskless — only the straddling
    # block pays the iota/select VPU work. split_boundary=False under the
    # interpreter (two-branch grid steps trip its vma unification, same
    # workaround as the flash kernels' split_diag).
    run = (j * block_t) < length
    if split_boundary:
        interior = (j * block_t + block_t - 1) <= offset

        @pl.when(run & interior)
        def _compute_interior():
            _accum(False)

        @pl.when(run & ~interior)
        def _compute_boundary():
            _accum(True)
    else:
        @pl.when(run)
        def _compute():
            _accum(True)

    @pl.when(j == num_t_blocks - 1)
    def _finalize():
        out, _ = _softmax_finalize(l_scr, acc_scr)
        o_ref[:] = out.astype(o_ref.dtype).reshape(o_ref.shape)


def _decode_pallas(q, k, v, length, layout, block_t, interpret):
    """q: (b, s, g, qpk, d); k/v per `layout`; length: scalar int32
    (traced OK) = offset + s valid cache positions. Returns
    (b, s, g, qpk, d) in q's dtype."""
    b, s, g, qpk, d = q.shape
    T = k.shape[2] if layout == "gtd" else k.shape[1]
    rows = s * qpk
    num_t_blocks = T // block_t
    assert T % block_t == 0

    qf = q.transpose(0, 2, 1, 3, 4).reshape(b, g, rows, d)
    # rows below one fp32 sublane tile: launch q/o in fp32 so Mosaic picks
    # a <1x128>-compatible layout for the small memref (the same
    # workaround JAX's paged-attention kernel ships for qpk % 8 != 0)
    out_dtype = q.dtype if rows % 8 == 0 else jnp.float32
    qf = qf.astype(out_dtype)

    kernel = functools.partial(
        _decode_kernel, block_t=block_t, rows=rows, qpk=qpk, d=d,
        num_t_blocks=num_t_blocks, sm_scale=1.0 / (d ** 0.5), s=s,
        split_boundary=not interpret,
    )

    def last_block(len_ref):
        # clamp past-the-end block indices to the last valid block: the
        # repeated index elides the DMA, so cache traffic follows the
        # CURRENT length, not the allocated T
        return jnp.minimum((len_ref[0] - 1) // block_t, num_t_blocks - 1)

    q_spec = pl.BlockSpec((None, None, rows, d),
                          lambda ib, ig, j, len_ref: (ib, ig, 0, 0))
    if layout == "gtd":
        kv_spec = pl.BlockSpec(
            (None, None, block_t, d),
            lambda ib, ig, j, len_ref: (
                ib, ig, jnp.minimum(j, last_block(len_ref)), 0
            ),
        )
    else:  # "tgd"
        kv_spec = pl.BlockSpec(
            (None, block_t, None, d),
            lambda ib, ig, j, len_ref: (
                ib, jnp.minimum(j, last_block(len_ref)), ig, 0
            ),
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, g, num_t_blocks),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct((b, g, rows, d), out_dtype, qf, k, v),
        # (b, g) steps are independent; only the cache dim carries the
        # online-softmax scratch state
        compiler_params=None if interpret else _compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(jnp.asarray(length, jnp.int32).reshape((1,)), qf, k, v)
    return out.reshape(b, g, s, qpk, d).transpose(0, 2, 1, 3, 4) \
        .astype(q.dtype)


# ---------------------------------------------------------------------------
# XLA reference (the pre-kernel decode math, both layouts): a layout shim
# over the shared `_xla_attend` dense core (ops/prefill_attention.py)
# ---------------------------------------------------------------------------


def _xla_decode(q, k, v, length, layout):
    """Batched-GEMM decode attention with the O(s*T) iota mask — the
    shapes-and-math twin of the kernel, used off-TPU and by the exact-
    match tests/bench comparisons."""
    b, s, g, qpk, d = q.shape
    if layout == "tgd":
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
    row_pos = (length - s) + jnp.arange(s * qpk) // qpk
    return _xla_attend(q, k, v, row_pos)


def decode_attention(
    q: jnp.ndarray,  # (b, s, g, qpk, d)
    k: jnp.ndarray,  # (b, g, T, d) "gtd" | (b, T, g, d) "tgd"
    v: jnp.ndarray,
    length,  # scalar int32 (traced OK): valid cache positions = offset + s
    layout: str = "gtd",
    use_pallas: Optional[bool] = None,
    block_t: int = DEFAULT_BLOCK_T,
    interpret: bool = False,
) -> jnp.ndarray:
    """KV-cached decode attention, (b, s, g, qpk, d) out. Positions
    >= `length` are masked in-kernel; within the step rows are causal
    (row r attends through position length - s + r)."""
    assert layout in ("gtd", "tgd"), layout
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        b, s, g, qpk, d = q.shape
        T = k.shape[2] if layout == "gtd" else k.shape[1]
        bt = decode_attn_block(s, qpk, d, T, requested=block_t,
                               interpret=interpret)
        if bt is not None:
            return _decode_pallas(q, k, v, length, layout, bt, interpret)
    return _xla_decode(q, k, v, length, layout)
