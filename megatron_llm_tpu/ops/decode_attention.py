"""Pallas decode-attention kernel — batched KV-cached decode at line rate.

The decode hot loop (inference/generation.py while_loop body) attends ONE
query token per sequence against the growing K/V cache. XLA lowers the
single-token QK/PV contractions to multiply-reduce loops that stream the
cache far below HBM bandwidth (measured r5: b=8 decode at 4.7 ms/step vs a
~3 ms weights+cache streaming floor — VERDICT r5 weak #2). This kernel
streams the cache through VMEM the way ops/flash_attention.py streams K/V
blocks in training, with decode-specific structure:

- grid (batch, group, cache_block): one grid step reads each K/V block
  ONCE per GQA group and serves all `q_per_kv` query heads of the group
  from it (the (position, head) fold of the flash kernel, with s == 1);
- online softmax in the exp2 domain (same running (m, l, acc) scheme and
  constants as the flash forward), accumulated in fp32 VMEM scratch;
- the VALID cache length rides a scalar-prefetch operand: block index
  maps clamp past-the-end blocks to the last valid block (Mosaic elides
  the repeated DMA, so masked grid steps cost no HBM traffic — the cache
  reads scale with the CURRENT length, not the allocated buffer), and
  in-kernel iota masking covers the straddling block — no dense
  (s, T) mask is ever materialized;
- two cache layouts, matching the two decode engines:
  "gtd" (b, g, T, d) — the per-layer standalone caches of the unrolled
  decode path (models/gpt.py init_kv_caches(layout="layers"));
  "tgd" (b, T, g, d) — the per-layer slice of the stacked (L, b, T, g, d)
  caches the pipelined stage-ring decode carries (parallel/pipeline.py).
  Both are consumed in place; neither is transposed or copied.

`decode_attention` dispatches to the kernel on TPU (or under
`interpret=True` through the Pallas interpreter — the CPU test path) and
to `_xla_decode`, a numerically matching reference, elsewhere.
`decode_attn_block` is the static viability check the model layer gates
on; it returns the chosen cache block size or None (XLA fallback).

PAGED VARIANT (ISSUE 3 tentpole, after Ragged Paged Attention — arxiv
2604.15464): `paged_decode_attention` serves the continuous-batching
engine (inference/engine.py). The cache is a GLOBAL page pool
(num_pages, page_size, g, d) shared by every slot; each slot owns a row
of a (slots, max_pages) page table plus a per-slot valid length. The
kernel is the same exp2 online softmax with two changes: the valid
length is read per grid row (`lengths[slot]`, not one shared scalar),
and the K/V block index map dereferences the scalar-prefetched page
table — grid step (slot, group, j) DMAs pool page
`page_table[slot, j]`, with past-the-length steps clamped to the slot's
last valid page so Mosaic elides the repeated DMA. Cache traffic
follows each slot's CURRENT length; slots at different lengths coexist
in one launch with zero padding traffic between them. Page 0 of the
pool is the NULL page by convention: unowned page-table entries point
at it and retired/inactive slots park there, so clamped DMAs always
have a real page to read. `_xla_paged_decode` (gather pages to the
dense "tgd" view, then the `_xla_decode` math) is the numerically
matching fallback and the CPU test oracle.

INT8 KV PAGES (ISSUE 9 tentpole): the paged variant also serves int8
pools — K/V stored int8 with per-(token, group) fp32 scales in parallel
(num_pages, page_size, g) scale pools (ops/quantization.py is the ONE
rounding/scale convention). The kernel DMAs the scale column with its
page through the same clamped index map and dequantizes in-register
before the unchanged fp32 online-softmax math; `_xla_paged_decode_quant`
(dequantize pools -> the fp twin) is the quantize-then-dequantize
oracle and the off-TPU serving path. Halves the decode kernel's HBM
cache traffic; quantization itself happens at write time in the
engine's scatter paths, never here.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from megatron_llm_tpu.ops.flash_attention import (
    LOG2E,
    NEG_INF,
    _compiler_params,
    _out_struct,
)

# swept space: 256 balances DMA amortization against the clamp granularity
# (past-the-end traffic is at most one block); _choose_block_t shrinks to
# the largest power-of-2 divisor of the allocated cache length.
DEFAULT_BLOCK_T = 256
# folded (position, head) rows per sequence-group — decode is s == 1 so
# this only bites exotic MQA configs (q_per_kv > 128)
MAX_DECODE_ROWS = 128


def _choose_block_t(T: int, requested: int = DEFAULT_BLOCK_T) -> Optional[int]:
    """Largest power-of-2 block <= requested dividing the allocated cache
    length T. Min 16 keeps bf16 sublane tiling; None -> XLA fallback."""
    b = 1 << (min(requested, T).bit_length() - 1)
    while b >= 16 and T % b:
        b //= 2
    return b if b >= 16 and T % b == 0 else None


def decode_attn_block(s: int, qpk: int, d: int, T: int, *,
                      min_cache: int = 0,
                      requested: int = DEFAULT_BLOCK_T,
                      interpret: bool = False) -> Optional[int]:
    """Static dispatch check for the decode kernel: returns the cache
    block size, or None when the XLA path should serve this shape.

    Kernel territory: single-token steps (s == 1 — prefill chunks keep
    the batched-GEMM path, which is compute- not bandwidth-bound), lane-
    aligned head_dim, an allocated cache at least `min_cache` long (below
    that the matvecs are too small for kernel launch overhead to pay),
    and a power-of-2 block dividing T. On CPU the kernel only runs under
    the interpreter (the test path); otherwise TPU-only, mirroring
    flash_attention's backend dispatch.
    """
    if not (interpret or jax.default_backend() == "tpu"):
        return None
    if s != 1 or s * qpk > MAX_DECODE_ROWS or d % 128 != 0:
        return None
    if T < max(min_cache, 16):
        return None
    return _choose_block_t(T, requested)


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, *rest, block_t, rows,
                   qpk, d, num_t_blocks, sm_scale, s, split_boundary=True,
                   batched_len=False, quantized=False):
    """Grid (b, g, num_t_blocks); the t dim carries the online-softmax
    state in VMEM scratch. Row r of the folded (rows, d) q block is query
    position offset + r // qpk (head fastest), offset = length - s.
    `batched_len` reads a PER-ROW length (the paged engine's ragged
    slots) instead of the dense path's one shared scalar. `quantized`
    (the int8-KV paged variant, ISSUE 9): k/v blocks arrive int8 with
    per-(token, group) fp32 scale columns as two extra (block_t, 1)
    operands, dequantized in-register before the same fp32 QK/PV math —
    the softmax/accumulation scheme is byte-identical to the fp path."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    j = pl.program_id(2)
    length = len_ref[pl.program_id(0)] if batched_len else len_ref[0]
    offset = length - s

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _accum(masked):
        # fp32 QK on tiny row counts: decode is cache-bandwidth-bound, so
        # MXU precision costs nothing; scores live in the exp2 domain
        # (sm_scale folded with log2(e), flash kernel convention)
        qb = q_ref[:].reshape(rows, d)
        kb = k_ref[:].reshape(block_t, d).astype(jnp.float32)
        if quantized:
            # dequantize in-register: one fp32 multiply per cache
            # element against the page's (block_t, 1) scale column —
            # HBM saw only the int8 bytes
            kb = kb * ks_ref[:].reshape(block_t, 1)
        sc = jax.lax.dot_general(
            qb.astype(jnp.float32), kb,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (sm_scale * LOG2E)
        if masked:
            # causal-within-step + cache-length mask in one predicate:
            # col c valid for row r iff c <= offset + r//qpk
            row_pos = offset + (
                jax.lax.broadcasted_iota(jnp.int32, (rows, block_t), 0)
                // qpk
            )
            col = j * block_t + jax.lax.broadcasted_iota(
                jnp.int32, (rows, block_t), 1
            )
            sc = jnp.where(col > row_pos, NEG_INF, sc)
        m_prev = m_scr[:]  # (rows, 1)
        m_cur = jnp.max(sc, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp2(m_prev - m_new)
        p = jnp.exp2(sc - m_new)  # (rows, block_t)
        l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
        if quantized:
            vb = v_ref[:].reshape(block_t, d).astype(jnp.float32) \
                * vs_ref[:].reshape(block_t, 1)
        else:
            vb = v_ref[:].reshape(block_t, d)
            p = p.astype(v_ref.dtype)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
            p, vb, preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    # blocks entirely past the valid length skip compute (their DMA was
    # clamped to the last valid block by the index map); interior blocks
    # (fully <= offset, every row) run maskless — only the straddling
    # block pays the iota/select VPU work. split_boundary=False under the
    # interpreter (two-branch grid steps trip its vma unification, same
    # workaround as the flash kernels' split_diag).
    run = (j * block_t) < length
    if split_boundary:
        interior = (j * block_t + block_t - 1) <= offset

        @pl.when(run & interior)
        def _compute_interior():
            _accum(False)

        @pl.when(run & ~interior)
        def _compute_boundary():
            _accum(True)
    else:
        @pl.when(run)
        def _compute():
            _accum(True)

    @pl.when(j == num_t_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[:] = (acc_scr[:] / l).astype(o_ref.dtype).reshape(o_ref.shape)


def _decode_pallas(q, k, v, length, layout, block_t, interpret):
    """q: (b, s, g, qpk, d); k/v per `layout`; length: scalar int32
    (traced OK) = offset + s valid cache positions. Returns
    (b, s, g, qpk, d) in q's dtype."""
    b, s, g, qpk, d = q.shape
    T = k.shape[2] if layout == "gtd" else k.shape[1]
    rows = s * qpk
    num_t_blocks = T // block_t
    assert T % block_t == 0

    qf = q.transpose(0, 2, 1, 3, 4).reshape(b, g, rows, d)
    # rows below one fp32 sublane tile: launch q/o in fp32 so Mosaic picks
    # a <1x128>-compatible layout for the small memref (the same
    # workaround JAX's paged-attention kernel ships for qpk % 8 != 0)
    out_dtype = q.dtype if rows % 8 == 0 else jnp.float32
    qf = qf.astype(out_dtype)

    kernel = functools.partial(
        _decode_kernel, block_t=block_t, rows=rows, qpk=qpk, d=d,
        num_t_blocks=num_t_blocks, sm_scale=1.0 / (d ** 0.5), s=s,
        split_boundary=not interpret,
    )

    def last_block(len_ref):
        # clamp past-the-end block indices to the last valid block: the
        # repeated index elides the DMA, so cache traffic follows the
        # CURRENT length, not the allocated T
        return jnp.minimum((len_ref[0] - 1) // block_t, num_t_blocks - 1)

    q_spec = pl.BlockSpec((None, None, rows, d),
                          lambda ib, ig, j, len_ref: (ib, ig, 0, 0))
    if layout == "gtd":
        kv_spec = pl.BlockSpec(
            (None, None, block_t, d),
            lambda ib, ig, j, len_ref: (
                ib, ig, jnp.minimum(j, last_block(len_ref)), 0
            ),
        )
    else:  # "tgd"
        kv_spec = pl.BlockSpec(
            (None, block_t, None, d),
            lambda ib, ig, j, len_ref: (
                ib, jnp.minimum(j, last_block(len_ref)), ig, 0
            ),
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, g, num_t_blocks),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct((b, g, rows, d), out_dtype, qf, k, v),
        # (b, g) steps are independent; only the cache dim carries the
        # online-softmax scratch state
        compiler_params=None if interpret else _compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(jnp.asarray(length, jnp.int32).reshape((1,)), qf, k, v)
    return out.reshape(b, g, s, qpk, d).transpose(0, 2, 1, 3, 4) \
        .astype(q.dtype)


# ---------------------------------------------------------------------------
# XLA reference (the pre-kernel decode math, both layouts)
# ---------------------------------------------------------------------------


def _xla_decode(q, k, v, length, layout):
    """Batched-GEMM decode attention with the O(s*T) iota mask — the
    shapes-and-math twin of the kernel, used off-TPU and by the exact-
    match tests/bench comparisons."""
    b, s, g, qpk, d = q.shape
    if layout == "tgd":
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
    T = k.shape[2]
    offset = length - s
    qb = q.transpose(0, 2, 1, 3, 4).reshape(b, g, s * qpk, d)
    scores = jax.lax.dot_general(
        qb, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    ) * (1.0 / jnp.sqrt(d).astype(jnp.float32))  # (b, g, s*qpk, T)
    row_pos = offset + jnp.arange(s * qpk) // qpk
    mask = jnp.arange(T)[None, :] > row_pos[:, None]
    scores = jnp.where(mask[None, None], jnp.finfo(jnp.float32).min, scores)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jax.lax.dot_general(
        probs, v, (((3,), (2,)), ((0, 1), (0, 1))),
    )  # (b, g, s*qpk, d)
    return out.reshape(b, g, s, qpk, d).transpose(0, 2, 1, 3, 4)


def decode_attention(
    q: jnp.ndarray,  # (b, s, g, qpk, d)
    k: jnp.ndarray,  # (b, g, T, d) "gtd" | (b, T, g, d) "tgd"
    v: jnp.ndarray,
    length,  # scalar int32 (traced OK): valid cache positions = offset + s
    layout: str = "gtd",
    use_pallas: Optional[bool] = None,
    block_t: int = DEFAULT_BLOCK_T,
    interpret: bool = False,
) -> jnp.ndarray:
    """KV-cached decode attention, (b, s, g, qpk, d) out. Positions
    >= `length` are masked in-kernel; within the step rows are causal
    (row r attends through position length - s + r)."""
    assert layout in ("gtd", "tgd"), layout
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        b, s, g, qpk, d = q.shape
        T = k.shape[2] if layout == "gtd" else k.shape[1]
        bt = decode_attn_block(s, qpk, d, T, requested=block_t,
                               interpret=interpret)
        if bt is not None:
            return _decode_pallas(q, k, v, length, layout, bt, interpret)
    return _xla_decode(q, k, v, length, layout)


# ---------------------------------------------------------------------------
# Paged variant: global page pool + per-slot page table (the
# continuous-batching serving cache, inference/engine.py)
# ---------------------------------------------------------------------------


def paged_decode_attn_block(s: int, qpk: int, d: int, page_size: int,
                            num_slot_pages: int, *,
                            min_cache: int = 0,
                            kv_dtype=None,
                            interpret: bool = False) -> Optional[int]:
    """Static dispatch check for the paged kernel: returns the block size
    (== page_size; the page IS the DMA unit) or None for the XLA path.

    Same territory as `decode_attn_block` — single-token steps,
    lane-aligned head dim, a big-enough cache — with the block constraint
    moved onto the page: `page_size` must tile sublanes (multiple of 16
    covers bf16; int8 pools need 32, the int8 sublane tile), and the
    per-slot reach num_slot_pages * page_size stands in for the
    allocated T of the dense gate.
    """
    if not (interpret or jax.default_backend() == "tpu"):
        return None
    if s != 1 or s * qpk > MAX_DECODE_ROWS or d % 128 != 0:
        return None
    is_int8 = kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8
    sublane = 32 if is_int8 else 16
    if page_size < sublane or page_size % sublane != 0:
        return None
    if num_slot_pages * page_size < max(min_cache, 16):
        return None
    return page_size


def _paged_pallas(q, k_pages, v_pages, page_table, lengths, interpret,
                  k_scales=None, v_scales=None):
    """q: (slots, 1, g, qpk, d); k/v_pages: (num_pages, page_size, g, d);
    page_table: (slots, max_pages) int32 pool indices; lengths: (slots,)
    int32 valid positions per slot (0 = empty slot -> zero output).
    k/v_scales (int8 pools only): (num_pages, page_size, g) fp32
    per-(token, group) scales, DMA'd page-by-page alongside the data
    through the same clamped index map. Returns (slots, 1, g, qpk, d)
    in q's dtype."""
    b, s, g, qpk, d = q.shape
    assert s == 1, "paged decode is single-token by construction"
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    rows = qpk
    quantized = k_scales is not None

    qf = q.transpose(0, 2, 1, 3, 4).reshape(b, g, rows, d)
    # same Mosaic small-memref workaround as the dense launcher: rows
    # below one fp32 sublane tile launch q/o in fp32
    out_dtype = q.dtype if rows % 8 == 0 else jnp.float32
    qf = qf.astype(out_dtype)

    body = functools.partial(
        _decode_kernel, block_t=page_size, rows=rows, qpk=qpk, d=d,
        num_t_blocks=max_pages, sm_scale=1.0 / (d ** 0.5), s=1,
        split_boundary=not interpret, batched_len=True,
        quantized=quantized,
    )

    def kernel(len_ref, pt_ref, *rest):
        # the page table is consumed entirely by the index maps; the
        # online-softmax body is the dense kernel's, fed per-slot lengths
        body(len_ref, *rest)

    def page_index(ib, j, len_ref, pt_ref):
        # past-the-length grid steps re-read the slot's LAST valid page
        # (repeated index -> elided DMA); empty slots (length 0) clamp to
        # table entry 0, which points at the pool's null page.
        last = jnp.maximum(len_ref[ib] - 1, 0) // page_size
        return pt_ref[ib, jnp.minimum(j, last)]

    q_spec = pl.BlockSpec(
        (None, None, rows, d),
        lambda ib, ig, j, len_ref, pt_ref: (ib, ig, 0, 0),
    )
    kv_spec = pl.BlockSpec(
        (None, page_size, None, d),
        lambda ib, ig, j, len_ref, pt_ref: (
            page_index(ib, j, len_ref, pt_ref), 0, ig, 0
        ),
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qf, k_pages, v_pages]
    if quantized:
        # the (page_size, 1) scale column of this (page, group): rides
        # the SAME clamped page index map as the data it scales
        scale_spec = pl.BlockSpec(
            (None, page_size, 1),
            lambda ib, ig, j, len_ref, pt_ref: (
                page_index(ib, j, len_ref, pt_ref), 0, ig
            ),
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, g, max_pages),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct((b, g, rows, d), out_dtype, qf, k_pages,
                              v_pages),
        compiler_params=None if interpret else _compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(jnp.asarray(lengths, jnp.int32), jnp.asarray(page_table, jnp.int32),
      *operands)
    return out.reshape(b, g, 1, qpk, d).transpose(0, 2, 1, 3, 4) \
        .astype(q.dtype)


def _xla_paged_decode(q, k_pages, v_pages, page_table, lengths):
    """Gather the owned pages into the dense (b, g, T, d) view, then the
    exact `_xla_decode` op sequence with per-row lengths — the
    shapes-and-math twin of the paged kernel, used off-TPU and by the
    engine's exact-match tests. Zero-probability columns (masked past
    each slot's length) multiply whatever the unwritten pool pages hold
    by an exact fp 0, so the gathered width never leaks into values."""
    b, s, g, qpk, d = q.shape
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    T = max_pages * page_size
    k = k_pages[page_table].reshape(b, T, g, d).transpose(0, 2, 1, 3)
    v = v_pages[page_table].reshape(b, T, g, d).transpose(0, 2, 1, 3)
    qb = q.transpose(0, 2, 1, 3, 4).reshape(b, g, s * qpk, d)
    scores = jax.lax.dot_general(
        qb, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    ) * (1.0 / jnp.sqrt(d).astype(jnp.float32))  # (b, g, s*qpk, T)
    row_pos = (lengths - s)[:, None] + jnp.arange(s * qpk)[None, :] // qpk
    mask = jnp.arange(T)[None, None, :] > row_pos[:, :, None]
    scores = jnp.where(mask[:, None], jnp.finfo(jnp.float32).min, scores)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jax.lax.dot_general(
        probs, v, (((3,), (2,)), ((0, 1), (0, 1))),
    )  # (b, g, s*qpk, d)
    # empty slots (length 0, every column masked): the softmax above
    # degenerates to uniform-over-garbage; pin them to the kernel's
    # exact-zero output so both paths share one contract
    out = jnp.where((lengths > 0)[:, None, None, None], out,
                    jnp.zeros((), out.dtype))
    return out.reshape(b, g, s, qpk, d).transpose(0, 2, 1, 3, 4)


def _xla_paged_decode_quant(q, k_pages, v_pages, k_scales, v_scales,
                            page_table, lengths):
    """Quantize-then-dequantize oracle for the int8 paged kernel:
    dequantize the int8 pools against their per-(token, group) scale
    pools to the fp32 view, then the exact `_xla_paged_decode` op
    sequence — what the in-register dequantization inside the kernel
    must reproduce (same fp32 values entering the same math). Off-TPU
    this IS the serving path (the engine's CPU fallback), so the oracle
    and the fallback can never drift."""
    kf = k_pages.astype(jnp.float32) * k_scales[..., None]
    vf = v_pages.astype(jnp.float32) * v_scales[..., None]
    return _xla_paged_decode(q, kf, vf, page_table, lengths)


def paged_decode_attention(
    q: jnp.ndarray,  # (slots, 1, g, qpk, d)
    k_pages: jnp.ndarray,  # (num_pages, page_size, g, d); int8 OK
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # (slots, max_pages) int32 pool indices
    lengths: jnp.ndarray,  # (slots,) int32 valid positions incl. this step
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
    k_scales: Optional[jnp.ndarray] = None,  # (num_pages, page_size, g)
    v_scales: Optional[jnp.ndarray] = None,  # fp32; required for int8
) -> jnp.ndarray:
    """Ragged paged decode attention: slot i attends its query token to
    cache positions 0..lengths[i]-1, streamed page-by-page from the pool
    through its page-table row. Positions past lengths[i] are masked
    in-kernel; a slot with lengths[i] == 0 returns zeros. Int8 pools
    (ISSUE 9) carry per-(token, group) fp32 scale pools and dequantize
    in-register (kernel) or on the gathered view (XLA twin)."""
    quantized = k_pages.dtype == jnp.int8
    if quantized:
        assert k_scales is not None and v_scales is not None, \
            "int8 KV pools require k_scales/v_scales"
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        b, s, g, qpk, d = q.shape
        bt = paged_decode_attn_block(
            s, qpk, d, k_pages.shape[1], page_table.shape[1],
            kv_dtype=k_pages.dtype,
            interpret=interpret,
        )
        if bt is not None:
            return _paged_pallas(q, k_pages, v_pages, page_table, lengths,
                                 interpret, k_scales=k_scales,
                                 v_scales=v_scales)
    if quantized:
        return _xla_paged_decode_quant(q, k_pages, v_pages, k_scales,
                                       v_scales, page_table, lengths)
    return _xla_paged_decode(q, k_pages, v_pages, page_table, lengths)
