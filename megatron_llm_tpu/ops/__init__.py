"""Pallas TPU kernels: flash attention (training), decode attention
(dense KV-cached serving), THE ragged paged attention kernel (every
phase of the continuous-batching engine — decode rows, ragged prompt
chunks, fp and int8 pools; ISSUE 18 collapsed the paged fork to this
one entry point), fused RMSNorm. Each module dispatches to a
numerically matching XLA path off-TPU; `interpret=True` runs the real
kernels through the Pallas interpreter (the CPU test suites)."""

from megatron_llm_tpu.ops.decode_attention import (  # noqa: F401
    decode_attention,
    decode_attn_block,
)
from megatron_llm_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_with_lse,
)
from megatron_llm_tpu.ops.prefill_attention import (  # noqa: F401
    ragged_paged_attention,
    ragged_paged_block,
)
