"""Pallas TPU kernels: flash attention (training), decode attention
(KV-cached serving), ragged paged prefill (chunked prompt admission),
fused RMSNorm. Each module dispatches to a numerically matching XLA
path off-TPU; `interpret=True` runs the real kernels through the Pallas
interpreter (the CPU test suites)."""

from megatron_llm_tpu.ops.decode_attention import (  # noqa: F401
    decode_attention,
    decode_attn_block,
)
from megatron_llm_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_with_lse,
)
from megatron_llm_tpu.ops.prefill_attention import (  # noqa: F401
    ragged_paged_prefill,
    ragged_prefill_block,
)
