"""Int8 quantization for the serving hot path (ISSUE 9).

Decode is bandwidth-bound: at single-token shapes every matvec and every
cache read streams its operand from HBM once per token, so bytes ARE
latency. This module holds the two quantization schemes the serving
stack uses and the ONE rounding/scale convention they share:

- **Int8 KV pages** (`quantize_rows` over the head dim): the engine's
  global page pools store K/V as int8 with a per-(token, group) fp32
  scale living in a parallel scale pool (num_pages, page_size, g) —
  ~4 bytes of scale per 2 x head_dim bytes of data. Quantization
  happens AT WRITE TIME through the ONE scatter path
  (ops/prefill_attention.scatter_chunk_kv — decode rows are its C == 1
  case since ISSUE 18); the ragged paged kernel dequantizes in-register
  inside its exp2-online-softmax loop (fp32 accumulation unchanged),
  and the XLA gather-pages twin dequantizes the gathered view — the
  same values either way, so the twin stays the CPU oracle.
- **Weight-only int8 decode matmuls** (`quantize_weight` per OUTPUT
  channel, `qdot` at the apply site): a one-shot transform of the fp
  decode param tree (GPTModel.prepare_decode_params(quantize_int8=
  True)) replaces each qkv/dense/MLP weight with
  {"int8_data", "scale"}; the decode GEMVs read half the weight bytes
  and apply the per-channel scale to the (tiny) output row. Activations
  are NOT quantized — at s == 1 they are noise next to the weight
  traffic, and keeping them fp keeps the scheme one-shot (no
  calibration). The fp path stays the default; training never sees
  quantized trees.

Numerics contract: symmetric round-to-nearest int8 (scale = amax/127,
no zero point — K/V and weights are zero-centered), dequantized error
<= scale/2 per element. An all-zero row quantizes to zeros with scale
0 and dequantizes to exact zeros (no NaN path). EQuARX (PAPERS.md)
motivates the "cheap symmetric scheme + fp32 accumulation" choice;
accuracy is measured, not assumed: bench.py `extra.quant` reports max
greedy logprob drift vs the bf16 path in-row, and docs/GUIDE.md
"Quantized serving" states the contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from megatron_llm_tpu.analysis.contracts import compile_contract

INT8_MAX = 127.0


def quantize_rows(x: jnp.ndarray, axis: int = -1):
    """Symmetric per-row int8 quantization over `axis`: scale =
    amax/127 (fp32), data = clip(round(x/scale)). Returns (int8 data,
    fp32 scales with `axis` removed). All-zero rows get scale 0 and
    round-trip to exact zeros."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis)
    scale = amax / INT8_MAX
    # guarded reciprocal: zero rows multiply by 0 instead of dividing
    # by 0 (dequantization multiplies by scale 0, so the round trip is
    # exact zeros either way)
    inv = jnp.where(scale > 0.0, 1.0 / jnp.maximum(scale, 1e-30), 0.0)
    data = jnp.clip(
        jnp.round(xf * jnp.expand_dims(inv, axis)), -INT8_MAX, INT8_MAX
    ).astype(jnp.int8)
    return data, scale


def dequantize_rows(data: jnp.ndarray, scale: jnp.ndarray,
                    axis: int = -1, dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of quantize_rows: data * scale broadcast over `axis`."""
    return (data.astype(jnp.float32)
            * jnp.expand_dims(scale, axis)).astype(dtype)


def scatter_quantized_rows(data_pool, scale_pool, pages, offs, x):
    """THE quantize-at-write point for int8 KV pools: quantize each
    (..., g, d) row of `x` over the head dim and write the int8 data
    and its fp32 scale at the SAME [pages, offs] of the paired pools.
    Every scatter path (chunked prefill, the single-token decode
    branch, the whole-prompt bucketed prefill) goes through this one
    definition, so the rounding/scale convention can never fork between
    writers."""
    data, scale = quantize_rows(x)
    return (data_pool.at[pages, offs].set(data),
            scale_pool.at[pages, offs].set(scale))


# ---------------------------------------------------------------------------
# Weight-only int8 (the decode matmuls)
# ---------------------------------------------------------------------------


def quantize_weight(w: jnp.ndarray) -> dict:
    """Per-OUTPUT-channel int8 for a (in_dim, out_dim) matmul weight:
    scale over axis 0, so `x @ W ~= (x @ int8) * scale[None, :]` — the
    scale application is a cheap per-column multiply on the GEMV output
    instead of a full dequantized weight materialization."""
    assert w.ndim == 2, (
        "weight-only quantization expects the 2D decode layout "
        f"(prepare_decode_params flattens GLU first), got {w.shape}")
    data, scale = quantize_rows(w, axis=0)
    return {"int8_data": data, "scale": scale}


def is_quantized_weight(w) -> bool:
    return isinstance(w, dict) and "int8_data" in w


def qdot(x: jnp.ndarray, w, dt) -> jnp.ndarray:
    """`x @ w` for a plain fp weight (bitwise-identical to the
    pre-quantization call sites: `x @ w.astype(dt)`) or a weight-only
    int8 dict (int8 operand streamed from HBM, converted in-register by
    the dot fusion, per-channel scale applied to the output in fp32
    then cast back to the compute dtype)."""
    if is_quantized_weight(w):
        y = x @ w["int8_data"].astype(dt)
        return (y.astype(jnp.float32) * w["scale"]).astype(dt)
    return x @ w.astype(dt)


@compile_contract(
    "ops.weight_quant",
    max_variants=1,  # ONE builder mint; per-model-shape executables
    # live in the jit call cache (the generate.tokens pattern,
    # jit_cache_size), not the variant store
    collectives={"single": frozenset()},
    tmp_bytes_budget=8 << 20,
    notes="one-shot fp->int8 decode-weight quantization; called once "
          "per engine at construction, never in a hot loop")
def _make_weight_quant_fn():
    """The jitted one-shot weight quantizer: maps the unrolled decode
    layer tuple (prepare_decode_params layout — per-layer standalone
    trees, GLU already flattened) to the weight-only int8 tree. Biases,
    norms, embeddings, and the LM head stay fp: their bytes are noise
    next to the four big GEMV weights, and the head's logit precision
    is exactly what the accuracy contract protects."""

    def quant_layers(layers):
        def one(layer):
            attn = dict(layer["attention"])
            mlp = dict(layer["mlp"])
            attn["wqkv"] = quantize_weight(attn["wqkv"])
            attn["wo"] = quantize_weight(attn["wo"])
            mlp["w1"] = quantize_weight(mlp["w1"])
            mlp["w2"] = quantize_weight(mlp["w2"])
            out = dict(layer)
            out["attention"] = attn
            out["mlp"] = mlp
            return out

        return tuple(one(layer) for layer in layers)

    # graft-contract: ops.weight_quant
    return jax.jit(quant_layers)


_weight_quant_fn = None


def weight_quant_fn():
    """The module-level cached quantizer executable (one jit, traced
    per layer-tree shape like every module-level entry point)."""
    global _weight_quant_fn
    if _weight_quant_fn is None:
        _weight_quant_fn = _make_weight_quant_fn()
    return _weight_quant_fn


def quantize_decode_layers(layers):
    """One-shot quantize of the unrolled decode layer tuple (the
    GPTModel.prepare_decode_params(quantize_int8=True) entry)."""
    return weight_quant_fn()(layers)
