"""THE ragged paged attention kernel (ISSUE 18 tentpole, after Ragged
Paged Attention — arxiv 2604.15464): one Pallas kernel serves every
inference phase of the continuous-batching engine.

The paged kernel family used to be a six-way fork — paged decode, ragged
prefill, and int8-quantized twins of both, next to flash (train) and
dense decode — the same exp2-online-softmax inner loop written ~6 ways,
each needing its own parity suite and its own GSPMD check under the tp
serving mesh. This module collapses the paged side to ONE kernel:

- **phase is a shape, not a variant**: a launch serves a batch of
  ragged QUERY CHUNKS — each a contiguous span of one slot's prompt at
  an arbitrary start offset — and a single-token decode row IS the
  width-1 chunk at offset `length` (chunk_lens == 1). The engine's
  decode scan, mixed prefill+decode rounds, and spec-verify steps all
  dispatch here (models/attention.py, ONE paged branch); the retired
  standalone paged decode entry is this kernel at C == 1, pinned
  bitwise by the suites before the fork was deleted.
- **kv dtype is a kernel parameter, not a variant**: fp pools run the
  plain epilogue; int8 pools (per-(token, group) fp32 scale columns in
  parallel scale pools, ISSUE 9) select the in-register dequant
  epilogue — the scale column rides the SAME clamped page index map as
  its data, and the fp32 online-softmax math is unchanged.
- **the mask/accumulator core is the shared template** of
  ops/flash_attention.py (`_causal_invalid` + `_softmax_init/accum/
  finalize`): flash instantiates it for dense training, the dense
  decode kernel for standalone caches, and this kernel for the paged
  pool — mask shapes are pluggable predicates: sliding-window
  attention (`window_size`) and packed-doc floors (`doc_starts`,
  ISSUE 19) are predicate parameterizations of this one body riding
  a double-ended DMA clamp, not new kernels.

Kernel structure:

- grid (chunk, group, q_block, page): each grid step reads one pool
  page ONCE per GQA group and serves all `q_per_kv` query heads of the
  group from it; the page dim carries the online-softmax state in VMEM
  scratch (exp2 domain, fp32 accumulation — the flash forward scheme);
- the per-chunk START OFFSET and VALID LENGTH ride scalar-prefetch
  operands: causal-within-chunk masking is `col <= start + row`, rows
  past the chunk's valid length are pad (exact-zero output), and the
  K/V index map dereferences the page table with past-the-need pages
  clamped to the last needed page — Mosaic elides the repeated DMA, so
  cache traffic follows `start + len`, not the allocated table width;
- interior/boundary split: page blocks fully below the causal diagonal
  and fully inside the valid length run maskless; only straddling
  blocks pay the iota/select VPU work (split_boundary=False under the
  interpreter, the same vma workaround as the flash/decode kernels).

`ragged_paged_attention` is the ONE public paged entry point (a tier-1
guard in tests/test_static_analysis.py holds it at one): it first
SCATTERS the chunk's own K/V into its slot's pages (valid rows only;
pad rows land on the pool's dead null page 0; int8 pools quantize at
write through ops/quantization.scatter_quantized_rows), then attends —
one jitted pass, so the chunk's in-span causal columns are read back
from the pool it just wrote. `_xla_paged_reference` (gather pages to
the dense view, then the `_xla_attend` dense core — also parameterized
by kv dtype) is the numerically matching fallback, the off-TPU serving
path, and the one test oracle; `interpret=True` runs the real kernel
through the Pallas interpreter.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from megatron_llm_tpu.ops.flash_attention import (
    LOG2E,
    NEG_INF,
    _causal_invalid,
    _compiler_params,
    _out_struct,
    _softmax_accum,
    _softmax_finalize,
    _softmax_init,
)

# folded (token, head) rows per grid program — the flash kernels' VMEM
# bound for the fp32 score block and accumulator
MAX_PAGED_ROWS = 2048


def _choose_block_q(C: int, qpk: int) -> Optional[int]:
    """Largest power-of-2 q block (in TOKENS) dividing the padded chunk
    width C with folded rows (block * qpk) under MAX_PAGED_ROWS.
    Chunks of any width >= 1 are served (the engine's width buckets are
    pow2; C == 1 is the decode row); None only when no divisor fits."""
    b = 1 << (C.bit_length() - 1)
    while b > 1 and (C % b or b * qpk > MAX_PAGED_ROWS):
        b //= 2
    return b if C % b == 0 and b * qpk <= MAX_PAGED_ROWS else None


def ragged_paged_block(s: int, qpk: int, d: int, page_size: int,
                       num_slot_pages: int, *,
                       min_cache: int = 0,
                       kv_dtype=None,
                       interpret: bool = False) -> Optional[int]:
    """Static dispatch check for the unified paged kernel: returns the
    q block size (tokens per grid program) or None for the XLA path.

    Kernel territory: lane-aligned head dim, a page that tiles sublanes
    (the page IS the K/V DMA unit — 16 covers bf16/fp32, int8 pools
    need the 32 int8 sublane tile), TPU-or-interpreter backend, and a
    per-slot reach num_slot_pages * page_size of at least `min_cache`.
    ONE gate for every phase: a decode row (s == 1) takes the same
    kernel-vs-XLA decision it would take as a width-1 chunk of a mixed
    step on the same pool, so a near-tie argmax can never flip when
    admission starts mid-stream.
    """
    if not (interpret or jax.default_backend() == "tpu"):
        return None
    if s < 1 or d % 128 != 0:
        return None
    is_int8 = kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8
    sublane = 32 if is_int8 else 16
    if page_size < sublane or page_size % sublane != 0:
        return None
    if num_slot_pages * page_size < max(min_cache, 16):
        return None
    return _choose_block_q(s, qpk)


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _paged_kernel(starts_ref, lens_ref, pt_ref, *rest, block_q,
                  page_size, qpk, d, num_pages, sm_scale,
                  split_boundary=True, quantized=False, window=None,
                  has_doc=False):
    """Grid (chunk, group, q_block, page); the page dim carries the
    online-softmax state. Row r of the folded (block_q*qpk, d) q block
    is chunk token i*block_q + r // qpk (head fastest) at global
    position starts[c] + token; rows at tokens >= lens[c] are pad.
    `quantized` selects the int8-KV epilogue (ISSUE 9): k/v arrive int8
    with per-(token, group) fp32 scale columns as two extra
    (page_size, 1) operands, dequantized in-register before the
    unchanged fp32 template math.

    Lower-bound masks (ISSUE 19) are extra parameterizations of the
    SAME body, not new kernels — both default off, and off means the
    emitted program is the pre-window one:
    - `window` (static int): sliding-window attention — row at
      position p attends cols [p - window + 1, p]. Pages wholly below
      the q block's FIRST row's window floor drop out of `run` (and
      the index map clamps them to the first needed page, eliding the
      DMA), pages below the LAST row's floor leave `interior`, so the
      window boundary pays the mask exactly like the causal boundary.
    - `has_doc`: a fourth scalar-prefetch operand doc_starts (nc,)
      gives each chunk an attention FLOOR (its packed document's first
      position); cols below it mask out, resetting causality at doc
      boundaries. Requires doc_starts[c] <= starts[c] so every valid
      row keeps its own diagonal column."""
    if has_doc:
        doc_ref, *rest = rest
    q_ref, k_ref, v_ref, *rest = rest
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    c = pl.program_id(0)
    i = pl.program_id(2)
    j = pl.program_id(3)
    rows = block_q * qpk
    start = starts_ref[c]
    clen = lens_ref[c]
    doc0 = doc_ref[c] if has_doc else None

    @pl.when(j == 0)
    def _init():
        _softmax_init(m_scr, l_scr, acc_scr)

    def _accum(masked):
        qb = q_ref[:].reshape(rows, d)
        kb = k_ref[:].reshape(page_size, d).astype(jnp.float32)
        if quantized:
            # dequantize in-register against the page's (page_size, 1)
            # scale column — HBM saw only the int8 bytes
            kb = kb * ks_ref[:].reshape(page_size, 1)
        sc = jax.lax.dot_general(
            qb.astype(jnp.float32), kb,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (sm_scale * LOG2E)
        if masked:
            # the shared causal predicate at the ragged-chunk
            # parameterization: token t of the chunk sits at position
            # start + t, may see cols <= start + t, and is pad when
            # t >= len (pad rows mask EVERY column -> the finalize
            # clamp emits exact zeros, the empty-slot contract).
            # NEG_INF is a finite constant: a PAD row would degenerate
            # to exp2(0)-everywhere garbage, so the finalize re-masks
            # pad rows; valid rows always have a real max (page 0,
            # col 0 is causal for every row), so their masked cells
            # underflow to exact 0.
            sc = jnp.where(
                _causal_invalid(rows, page_size, qpk,
                                start + i * block_q, j * page_size,
                                valid_rows=clen - i * block_q,
                                window=window, floor=doc0),
                NEG_INF, sc,
            )
        if quantized:
            vb = v_ref[:].reshape(page_size, d).astype(jnp.float32) \
                * vs_ref[:].reshape(page_size, 1)
            _softmax_accum(sc, vb, m_scr, l_scr, acc_scr)
        else:
            _softmax_accum(sc, v_ref[:].reshape(page_size, d), m_scr,
                           l_scr, acc_scr, p_dtype=v_ref.dtype)

    # last position this q block's VALID rows can attend: the block's
    # last valid token (or nothing when the block is all pad)
    blk_last_tok = jnp.minimum((i + 1) * block_q, clen) - 1
    run = (i * block_q < clen) & \
        ((j * page_size) <= (start + blk_last_tok))
    if window is not None or has_doc:
        # symmetric lower skip: pages wholly below even the FIRST
        # row's floor serve no row of this q block. For window >=
        # context the floor is never positive and the predicate (like
        # the clamp) never binds — bitwise the dense program.
        first_lo = jnp.int32(0)
        if window is not None:
            first_lo = jnp.maximum(first_lo,
                                   start + i * block_q - (window - 1))
        if has_doc:
            first_lo = jnp.maximum(first_lo, doc0)
        run = run & ((j * page_size + page_size - 1) >= first_lo)
    if split_boundary:
        # maskless when every row is valid AND every column is causal
        # for even the block's FIRST token
        interior = ((i + 1) * block_q <= clen) & \
            ((j * page_size + page_size - 1) <= (start + i * block_q))
        if window is not None:
            # ... AND in-window for even the LAST token's floor
            interior = interior & \
                ((j * page_size) >= (start + (i + 1) * block_q - window))
        if has_doc:
            interior = interior & ((j * page_size) >= doc0)

        @pl.when(run & interior)
        def _compute_interior():
            _accum(False)

        @pl.when(run & ~interior)
        def _compute_boundary():
            _accum(True)
    else:
        @pl.when(run)
        def _compute():
            _accum(True)

    @pl.when(j == num_pages - 1)
    def _finalize():
        out, _ = _softmax_finalize(l_scr, acc_scr)
        # pad rows accumulated garbage above (see the mask note): pin
        # them to the exact-zero contract of the XLA twin
        row_tok = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (rows, d), 0) // qpk
        out = jnp.where(row_tok < clen, out, 0.0)
        o_ref[:] = out.astype(o_ref.dtype).reshape(o_ref.shape)


def _paged_pallas(q, k_pages, v_pages, page_table, starts, chunk_lens,
                  block_q, interpret, k_scales=None, v_scales=None,
                  window=None, doc_starts=None):
    """q: (nc, C, g, qpk, d); k/v_pages: (P, page_size, g, d);
    page_table: (nc, max_pages) int32; starts/chunk_lens: (nc,) int32.
    k/v_scales (int8 pools only): (P, page_size, g) fp32 per-(token,
    group) scales riding the same clamped page index map. `window`
    (static) / `doc_starts` ((nc,) int32, a 4th scalar-prefetch
    operand) add the ISSUE 19 lower bounds: the page index map then
    clamps BOTH ends, so out-of-window / pre-document pages repeat an
    in-bound index and Mosaic elides their DMAs — decode-row traffic
    is O(window), not O(context). Returns (nc, C, g, qpk, d) in q's
    dtype (pad rows exact zero)."""
    nc, C, g, qpk, d = q.shape
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    rows = block_q * qpk
    num_q_blocks = C // block_q
    quantized = k_scales is not None
    has_doc = doc_starts is not None

    qf = q.transpose(0, 2, 1, 3, 4).reshape(nc, g, C * qpk, d)
    # rows below one fp32 sublane tile: launch q/o in fp32 (the small-
    # memref Mosaic workaround shared with the dense decode kernel)
    out_dtype = q.dtype if rows % 8 == 0 else jnp.float32
    qf = qf.astype(out_dtype)

    kernel = functools.partial(
        _paged_kernel, block_q=block_q, page_size=page_size, qpk=qpk,
        d=d, num_pages=max_pages, sm_scale=1.0 / (d ** 0.5),
        split_boundary=not interpret, quantized=quantized,
        window=window, has_doc=has_doc,
    )

    def page_index(c, i, j, starts_ref, lens_ref, pt_ref, doc_ref=None):
        # clamp past-the-need page indices to the LAST page this q block
        # attends (repeated index -> elided DMA): traffic follows
        # start + len, not the allocated table width. All-pad blocks and
        # empty chunks clamp to table entry 0 (the slot's null-page
        # parking by engine convention — always a real, dead page).
        last_tok = jnp.minimum((i + 1) * block_q,
                               jnp.maximum(lens_ref[c], 1)) - 1
        last = jnp.clip((starts_ref[c] + last_tok) // page_size,
                        0, max_pages - 1)
        if window is None and doc_ref is None:
            return pt_ref[c, jnp.minimum(j, last)]
        # symmetric LOWER clamp (ISSUE 19): pages wholly before the q
        # block's first row's window floor / the chunk's document
        # start repeat the first needed page — same elision, so the
        # engine may reclaim the pages behind it (the kernel can never
        # dereference a table entry below `first` by construction).
        # window >= context keeps the floor at 0 == bitwise-dense.
        lo = jnp.int32(0)
        if window is not None:
            lo = jnp.maximum(
                lo, starts_ref[c] + i * block_q - (window - 1))
        if doc_ref is not None:
            lo = jnp.maximum(lo, doc_ref[c])
        first = jnp.clip(lo // page_size, 0, max_pages - 1)
        return pt_ref[c, jnp.clip(j, first, last)]

    q_spec = pl.BlockSpec(
        (None, None, rows, d),
        lambda c, gi, i, j, *s_refs: (c, gi, i, 0),
    )
    kv_spec = pl.BlockSpec(
        (None, page_size, None, d),
        lambda c, gi, i, j, *s_refs: (
            page_index(c, i, j, *s_refs), 0, gi, 0
        ),
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qf, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec(
            (None, page_size, 1),
            lambda c, gi, i, j, *s_refs: (
                page_index(c, i, j, *s_refs), 0, gi
            ),
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales, v_scales]
    scalars = [jnp.asarray(starts, jnp.int32),
               jnp.asarray(chunk_lens, jnp.int32),
               jnp.asarray(page_table, jnp.int32)]
    if has_doc:
        scalars.append(jnp.asarray(doc_starts, jnp.int32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=(nc, g, num_q_blocks, max_pages),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct((nc, g, C * qpk, d), out_dtype, qf, k_pages,
                              v_pages),
        # (chunk, group, q_block) steps are independent; only the page
        # dim carries the online-softmax scratch state
        compiler_params=None if interpret else _compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(*scalars, *operands)
    return out.reshape(nc, g, C, qpk, d).transpose(0, 2, 1, 3, 4) \
        .astype(q.dtype)


# ---------------------------------------------------------------------------
# XLA reference: ONE gather-pages-then-dense definition (ISSUE 18
# satellite — the former per-variant oracle twins, paged decode and
# ragged prefill each with a quantized sibling, collapsed)
# ---------------------------------------------------------------------------


def _xla_attend(q, k, v, row_pos, row_valid=None, row_lo=None):
    """The dense masked-softmax core every XLA attention twin shares:
    q (b, s, g, qpk, d) against dense k/v (b, g, T, d). `row_pos` is the
    last attendable cache position per folded row — (rows,) when shared
    across the batch (the dense decode twin), (b, rows) when ragged per
    sequence (the paged twin). `row_valid` (b, rows), optional: rows
    where False pin to exact zero (the pad-row / empty-chunk contract);
    None skips the select entirely so the dense twin's HLO is
    unchanged. `row_lo` (b, rows), optional: the FIRST attendable cache
    position per folded row (the sliding-window / packed-doc lower
    bound, ISSUE 19) — None skips that select the same way. Masked
    columns multiply unwritten (or reclaimed-and-reused) cache by an
    exact fp 0, so the allocated width never leaks into values."""
    b, s, g, qpk, d = q.shape
    T = k.shape[2]
    qb = q.transpose(0, 2, 1, 3, 4).reshape(b, g, s * qpk, d)
    scores = jax.lax.dot_general(
        qb, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    ) * (1.0 / jnp.sqrt(d).astype(jnp.float32))  # (b, g, s*qpk, T)
    if row_pos.ndim == 1:
        mask = jnp.arange(T)[None, :] > row_pos[:, None]
        scores = jnp.where(mask[None, None], jnp.finfo(jnp.float32).min,
                           scores)
    else:
        mask = jnp.arange(T)[None, None, :] > row_pos[:, :, None]
        scores = jnp.where(mask[:, None], jnp.finfo(jnp.float32).min,
                           scores)
    if row_lo is not None:
        lo_mask = jnp.arange(T)[None, None, :] < row_lo[:, :, None]
        scores = jnp.where(lo_mask[:, None], jnp.finfo(jnp.float32).min,
                           scores)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jax.lax.dot_general(
        probs, v, (((3,), (2,)), ((0, 1), (0, 1))),
    )  # (b, g, s*qpk, d)
    if row_valid is not None:
        out = jnp.where(row_valid[:, None, :, None], out,
                        jnp.zeros((), out.dtype))
    return out.reshape(b, g, s, qpk, d).transpose(0, 2, 1, 3, 4)


def _xla_paged_reference(q, k_pages, v_pages, page_table, starts,
                         chunk_lens, k_scales=None, v_scales=None,
                         window=None, doc_starts=None):
    """Gather each chunk's pages into the dense view, then the
    `_xla_attend` core with ragged per-chunk row positions — the
    shapes-and-math twin of the kernel, the off-TPU serving path, and
    the ONE parity-test oracle. kv dtype is a parameter here too:
    int8 pools pass their scale pools and dequantize to the fp32 view
    first (the quantize-then-dequantize oracle — the same fp32 values
    the kernel's in-register epilogue feeds the same math). Pad rows
    (token >= chunk_lens) pin to the kernel's exact-zero output.
    `window` / `doc_starts` (ISSUE 19) become a per-row lower bound
    row_lo = max(pos - window + 1, doc_starts[c], 0): this path
    GATHERS every table entry (reclaimed entries park on null page 0),
    but the lower mask multiplies those columns by an exact fp 0, so
    mid-flight page reclamation is bitwise-invisible here too."""
    nc, C, g, qpk, d = q.shape
    if k_scales is not None:
        k_pages = k_pages.astype(jnp.float32) * k_scales[..., None]
        v_pages = v_pages.astype(jnp.float32) * v_scales[..., None]
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    T = max_pages * page_size
    k = k_pages[page_table].reshape(nc, T, g, d).transpose(0, 2, 1, 3)
    v = v_pages[page_table].reshape(nc, T, g, d).transpose(0, 2, 1, 3)
    tok = jnp.arange(C * qpk) // qpk  # (rows,)
    row_pos = starts[:, None] + tok[None, :]  # (nc, rows)
    row_valid = tok[None, :] < chunk_lens[:, None]  # (nc, rows)
    row_lo = None
    if window is not None or doc_starts is not None:
        row_lo = jnp.zeros_like(row_pos)
        if window is not None:
            row_lo = jnp.maximum(row_lo, row_pos - (window - 1))
        if doc_starts is not None:
            row_lo = jnp.maximum(row_lo, doc_starts[:, None])
    return _xla_attend(q, k, v, row_pos, row_valid=row_valid,
                       row_lo=row_lo)


def scatter_chunk_kv(k_new, v_new, k_pages, v_pages, page_table, starts,
                     chunk_lens, k_scales=None, v_scales=None):
    """Write a chunk's K/V rows into its slot's pages: token t (valid,
    t < chunk_lens) lands in pool page page_table[c, (starts+t) //
    page_size] at offset (starts+t) % page_size. Pad rows are routed to
    pool page 0 — the dead null page every table parks unowned entries
    on — so they can never touch a live slot's cache. Returns the
    updated pools. The decode scan's single-token write is the C == 1
    case of this one scatter (retired slots carry all-null table rows,
    so their row lands on the null page like a pad row would).

    Int8 pools (k_pages.dtype == int8; pass the matching k/v_scales
    pools): this IS the quantize-at-write point — k_new/v_new arrive fp,
    each (token, group) row quantizes symmetrically over the head dim
    (ops/quantization.quantize_rows), the int8 data lands in the data
    pools and the fp32 scales land at the SAME [page, offset] of the
    scale pools (pad-row scales go to the null page with their data).
    Returns (k_pages, v_pages, k_scales, v_scales)."""
    nc, C = k_new.shape[:2]
    page_size = k_pages.shape[1]
    max_pages = page_table.shape[1]
    quantized = k_pages.dtype == jnp.int8
    pos = starts[:, None] + jnp.arange(C)[None, :]  # (nc, C)
    valid = jnp.arange(C)[None, :] < chunk_lens[:, None]
    logical = jnp.clip(pos // page_size, 0, max_pages - 1)
    pages = jnp.where(
        valid, jnp.take_along_axis(page_table, logical, axis=1), 0)
    offs = pos % page_size
    if quantized:
        from megatron_llm_tpu.ops.quantization import (
            scatter_quantized_rows,
        )

        assert k_scales is not None and v_scales is not None, \
            "int8 KV pools require k_scales/v_scales"
        k_pages, k_scales = scatter_quantized_rows(
            k_pages, k_scales, pages, offs, k_new)
        v_pages, v_scales = scatter_quantized_rows(
            v_pages, v_scales, pages, offs, v_new)
        return k_pages, v_pages, k_scales, v_scales
    k_pages = k_pages.at[pages, offs].set(k_new.astype(k_pages.dtype))
    v_pages = v_pages.at[pages, offs].set(v_new.astype(v_pages.dtype))
    return k_pages, v_pages


def ragged_paged_attention(
    q: jnp.ndarray,  # (nc, C, g, qpk, d) — C = padded chunk width
    k_new: jnp.ndarray,  # (nc, C, g, d) — this chunk's K (RoPE applied)
    v_new: jnp.ndarray,  # (nc, C, g, d)
    k_pages: jnp.ndarray,  # (num_pages, page_size, g, d); int8 OK
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # (nc, max_pages) int32 pool indices
    starts: jnp.ndarray,  # (nc,) int32 — chunk start offset in the slot
    chunk_lens: jnp.ndarray,  # (nc,) int32 valid tokens (<= C; 0 = idle)
    use_pallas: Optional[bool] = None,
    min_cache: int = 0,
    interpret: bool = False,
    k_scales: Optional[jnp.ndarray] = None,  # (num_pages, page_size, g)
    v_scales: Optional[jnp.ndarray] = None,  # fp32; required for int8
    window_size: Optional[int] = None,  # static; None/<=0 = full causal
    doc_starts: Optional[jnp.ndarray] = None,  # (nc,) int32 doc floors
):
    """THE paged attention entry point, one pass for every phase:
    scatter the chunk's own K/V into its slot's pages, then causal
    attention of chunk token t (global position starts + t) over cache
    positions 0..starts+t — served by the Pallas kernel on TPU (or
    under the interpreter) and by the gather-pages twin elsewhere.

    Phase is a shape: a decode row is chunk_lens == 1 at starts ==
    lengths (C == 1 in the engine's decode scan; any C in a mixed
    round), a prefill span is chunk_lens in 2..C, an idle slot is
    chunk_lens == 0. Returns (out (nc, C, g, qpk, d), k_pages,
    v_pages); pad rows (t >= chunk_lens) are exact zeros.

    kv dtype is a parameter (ISSUE 9): int8 pools pass the fp32 scale
    pools too — the scatter quantizes the chunk's fp K/V at write time,
    attention dequantizes in-register (kernel) or on the gathered view
    (XLA twin), and the return grows to (out, k_pages, v_pages,
    k_scales, v_scales).

    Window is a parameter too (ISSUE 19): `window_size` W restricts
    token t to cache positions [max(0, starts + t - W + 1), starts + t]
    in BOTH paths — the kernel's double-ended DMA clamp makes the read
    O(W), the twin masks the same columns to exact-0 probabilities, and
    W >= starts + chunk_lens (window covers the context) is bitwise the
    W=None program, so the engine may reclaim pages wholly below every
    live window. `doc_starts` (per-chunk floors, doc_starts[c] <=
    starts[c]) packs multiple documents into one ragged launch with
    zero cross-doc attention: give each document its own chunk over the
    same slot pages and its own start, floored at its first position.
    Both default to None == the pre-ISSUE-19 trace, byte-identical."""
    nc, C, g, qpk, d = q.shape
    if window_size is not None and window_size <= 0:
        window_size = None
    quantized = k_pages.dtype == jnp.int8
    if quantized:
        k_pages, v_pages, k_scales, v_scales = scatter_chunk_kv(
            k_new, v_new, k_pages, v_pages, page_table, starts,
            chunk_lens, k_scales=k_scales, v_scales=v_scales)
    else:
        k_pages, v_pages = scatter_chunk_kv(
            k_new, v_new, k_pages, v_pages, page_table, starts,
            chunk_lens)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        bq = ragged_paged_block(C, qpk, d, k_pages.shape[1],
                                page_table.shape[1],
                                min_cache=min_cache,
                                kv_dtype=k_pages.dtype,
                                interpret=interpret)
        if bq is not None:
            out = _paged_pallas(q, k_pages, v_pages, page_table,
                                starts, chunk_lens, bq, interpret,
                                k_scales=k_scales, v_scales=v_scales,
                                window=window_size,
                                doc_starts=doc_starts)
            if quantized:
                return out, k_pages, v_pages, k_scales, v_scales
            return out, k_pages, v_pages
    out = _xla_paged_reference(q, k_pages, v_pages, page_table, starts,
                               chunk_lens, k_scales=k_scales,
                               v_scales=v_scales, window=window_size,
                               doc_starts=doc_starts)
    if quantized:
        return out, k_pages, v_pages, k_scales, v_scales
    return out, k_pages, v_pages
