"""Typed configuration for megatron_llm_tpu.

Replaces the reference's 1075-line argparse tree (ref: arguments.py:14-345)
and its global-singleton access pattern (ref: global_vars.py:22-67) with
plain frozen dataclasses passed explicitly. The flag surface mirrors the
groups catalogued in SURVEY.md §2.5: network_size, regularization, training,
initialization, learning-rate, checkpointing, mixed precision, distributed,
validation, data.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional

import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Activation-recompute policy vocabulary (the registry's NAMES; the jax
# policy objects live in models/remat.py so this module stays import-light).
#
# The ladder, cheapest-memory first (FLOPs move the other way):
#   "full"      — jax.checkpoint with no policy: save only the layer
#                 boundary carry, recompute everything (+~1/3 FLOPs).
#   "offload"   — save the named matmul outputs like "selective" but park
#                 them in pinned HOST memory (save_and_offload_only_these_
#                 names): device HBM like "full", FLOPs like "selective",
#                 paid for in PCIe/DMA traffic — the long-sequence lever.
#   "selective" — save_only_these_names(...) over the named save points
#                 (models/remat.py CHECKPOINT_NAMES): keep the big matmul
#                 outputs, recompute only cheap elementwise ops. Megatron's
#                 "selective" granularity, generalized.
#   "save_dots" — jax.checkpoint_policies.checkpoint_dots: keep EVERY dot
#                 output (named or not); FLOP floor, more live HBM.
#   "none"      — no remat: AD saves whatever it wants (highest memory).
# ---------------------------------------------------------------------------

REMAT_POLICIES = ("full", "selective", "save_dots", "offload", "none")

# back-compat mapping from the reference's --recompute_granularity surface
_GRANULARITY_TO_POLICY = {None: "none", "selective": "selective",
                          "full": "full"}


# ---------------------------------------------------------------------------
# Model architecture
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters (ref: arguments.py:406-474 network_size group)."""

    num_layers: int = 2
    hidden_size: int = 128
    ffn_hidden_size: Optional[int] = None  # default 4*h, or derived for GLU presets
    num_attention_heads: int = 4
    # GQA/MQA: number of distinct KV heads (ref: arguments.py:420
    # --num_attention_heads_kv; MQA when 1, GQA when 1<kv<heads).
    num_attention_heads_kv: Optional[int] = None
    kv_channels: Optional[int] = None  # head_dim; default hidden/heads
    max_position_embeddings: int = 2048
    seq_length: int = 2048
    padded_vocab_size: int = 0  # set by tokenizer padding (see pad_vocab_size)
    make_vocab_size_divisible_by: int = 128

    # Norms (ref: arguments.py:434-445, fused_layer_norm.py:64-139)
    layernorm_epsilon: float = 1e-5
    use_rms_norm: bool = False
    use_post_ln: bool = False  # post-LN (BERT-style) vs default pre-LN

    # Projections / activations (ref: arguments.py:439-452)
    use_bias: bool = True
    glu_activation: Optional[str] = None  # liglu|geglu|reglu|swiglu
    hidden_act: str = "gelu"  # used when glu_activation is None

    # Position embeddings (ref: arguments.py:456-463, positional_embeddings.py)
    position_embedding_type: str = "absolute"  # absolute | rotary
    rope_scaling_factor: float = 1.0
    rope_theta: float = 10000.0

    # Falcon-style structure (ref: arguments.py:465-468, transformer.py:774-806)
    parallel_attn: bool = False  # attention and MLP read the same LN, summed
    parallel_layernorm: bool = False  # separate LN for MLP input (Falcon-40B)

    # Embedding/head tying (ref: arguments.py:470-473, gpt_model.py:56-78)
    tie_embed_logits: bool = True

    # Regularization (ref: arguments.py:544-574)
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    lima_dropout: bool = False  # layer-index-scaled dropout (ref: transformer.py:964-971)

    # Precision (ref: arguments.py:783-815)
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    fp32_residual_connection: bool = False
    # NOTE deliberately absent: apply_query_key_layer_scaling and
    # attention_softmax_in_fp32 (ref arguments.py:632-650). Both exist to
    # keep fp16 softmax in range; this build ALWAYS computes attention
    # scores/softmax in fp32 (models/attention.py, ops/flash_attention.py),
    # which is the apply_query_key_layer_scaling=False +
    # attention_softmax_in_fp32=True behavior, so the knobs would be lies.

    # Init (ref: arguments.py:694-705, layers.py:79-125)
    init_method_std: float = 0.02
    use_scaled_init_method: bool = True  # output layers scaled by 1/sqrt(2L)

    # Recompute (ref: arguments.py:606-630). `recompute_granularity` keeps
    # the reference vocabulary; `remat_policy` is the first-class policy
    # name (REMAT_POLICIES above). Give ONE of them — when both are given
    # they must agree (full<->full, selective<->selective) or __post_init__
    # raises, so a script can never silently train with the wrong
    # memory/FLOP trade. `resolved_remat_policy` is what the model reads.
    recompute_granularity: Optional[str] = None  # None | "selective" | "full"
    remat_policy: Optional[str] = None  # None | one of REMAT_POLICIES
    recompute_method: str = "uniform"  # "uniform" | "block"
    recompute_num_layers: int = 1

    # Kernels
    use_flash_attn: bool = False  # Pallas flash-attention path
    use_fused_rmsnorm: bool = False  # Pallas fused RMSNorm path
    # Pallas decode-attention kernel (ops/decode_attention.py) on the
    # KV-cached single-token path. Default ON: off-TPU it falls back to
    # the XLA decode math unless decode_attn_interpret routes the real
    # kernel through the Pallas interpreter (the CPU test path).
    use_decode_attn: bool = True
    # below this allocated cache length the XLA matvecs win (kernel
    # launch overhead dominates a cache this small)
    decode_attn_min_cache: int = 128
    decode_attn_interpret: bool = False
    # Sliding-window attention on the PAGED serving path (ISSUE 19):
    # a token at position p attends [max(0, p - W + 1), p]. None = full
    # causal; W >= context is bitwise full-causal. Static — baked into
    # the serving traces, and the engine reclaims pages wholly out of
    # every live window mid-flight. Serving-side only for now: the
    # dense training paths ignore it (GUIDE "Long-context serving").
    attention_window_size: Optional[int] = None

    # BERT/T5 family (ref: --num_tokentypes language_model.py:160-170;
    # bert_binary_head bert_model.py:130)
    num_tokentypes: int = 0
    add_binary_head: bool = False

    def __post_init__(self):
        if self.kv_channels is None:
            object.__setattr__(
                self, "kv_channels", self.hidden_size // self.num_attention_heads
            )
        if self.num_attention_heads_kv is None:
            object.__setattr__(self, "num_attention_heads_kv", self.num_attention_heads)
        if self.ffn_hidden_size is None:
            object.__setattr__(self, "ffn_hidden_size", 4 * self.hidden_size)
        assert self.num_attention_heads % self.num_attention_heads_kv == 0
        if self.attention_window_size is not None \
                and self.attention_window_size < 1:
            raise ValueError(
                "attention_window_size must be >= 1 (or None for full "
                f"causal attention), got {self.attention_window_size}")
        # Recompute-policy validation: unknown strings raise HERE, at config
        # construction, never downstream as a silently-wrong memory/FLOP
        # trade (the pre-policy code mapped granularity="selective" to "no
        # remat at all" without a word).
        if self.recompute_granularity not in _GRANULARITY_TO_POLICY:
            raise ValueError(
                f"recompute_granularity={self.recompute_granularity!r}: "
                f"expected one of {sorted(k for k in _GRANULARITY_TO_POLICY if k)} "
                f"or None"
            )
        if self.remat_policy is not None \
                and self.remat_policy not in REMAT_POLICIES:
            raise ValueError(
                f"remat_policy={self.remat_policy!r}: expected one of "
                f"{REMAT_POLICIES} or None"
            )
        if self.recompute_method not in ("uniform", "block"):
            raise ValueError(
                f"recompute_method={self.recompute_method!r}: expected "
                f"'uniform' or 'block'"
            )
        if (self.remat_policy is not None
                and self.recompute_granularity is not None
                and _GRANULARITY_TO_POLICY[self.recompute_granularity]
                != self.remat_policy):
            raise ValueError(
                f"conflicting recompute flags: "
                f"recompute_granularity={self.recompute_granularity!r} "
                f"implies remat_policy="
                f"{_GRANULARITY_TO_POLICY[self.recompute_granularity]!r} "
                f"but remat_policy={self.remat_policy!r} was given; "
                f"specify one, or make them agree"
            )
        # method/num_layers only do anything under an active policy /
        # block splits — requesting them in a dead combination is the same
        # silent-misconfiguration class the checks above exist to reject
        if self.recompute_method == "block" \
                and self.resolved_remat_policy == "none":
            raise ValueError(
                "recompute_method='block' does nothing without an active "
                "remat policy: also pass remat_policy "
                "(full/selective/save_dots/offload) or "
                "recompute_granularity (full/selective)"
            )
        if self.recompute_num_layers != 1 and self.recompute_method != "block":
            raise ValueError(
                f"recompute_num_layers={self.recompute_num_layers} is only "
                f"read by recompute_method='block' (uniform remats every "
                f"layer); drop it or request block splits"
            )

    # -- derived ----------------------------------------------------------
    @property
    def resolved_remat_policy(self) -> str:
        """The active policy name (one of REMAT_POLICIES): `remat_policy`
        when given, else the reference-vocabulary mapping of
        `recompute_granularity` (None->none, selective->selective,
        full->full). __post_init__ guarantees the two agree."""
        if self.remat_policy is not None:
            return self.remat_policy
        return _GRANULARITY_TO_POLICY[self.recompute_granularity]

    @property
    def head_dim(self) -> int:
        return self.kv_channels

    @property
    def num_query_groups(self) -> int:
        return self.num_attention_heads_kv

    @property
    def q_per_kv(self) -> int:
        return self.num_attention_heads // self.num_attention_heads_kv

    @property
    def qkv_projection_size(self) -> int:
        # ref: transformer.py:316 — n*hd + 2*n_kv*hd, grouped layout.
        return self.kv_channels * (
            self.num_attention_heads + 2 * self.num_attention_heads_kv
        )

    @property
    def mlp_input_size(self) -> int:
        # GLU doubles the up-projection width (ref: transformer.py:92-102).
        mult = 2 if self.glu_activation else 1
        return mult * self.ffn_hidden_size

    def pad_vocab_size(self, vocab_size: int, tp: int = 1) -> int:
        """Pad vocab so it divides evenly over TP ranks (ref: tokenizer.py:49-63)."""
        multiple = self.make_vocab_size_divisible_by * tp
        return ((vocab_size + multiple - 1) // multiple) * multiple


# ---------------------------------------------------------------------------
# Parallel layout
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ParallelConfig:
    """Device-mesh layout (ref: parallel_state.py:51-214, arguments.py:820-866).

    The reference builds NCCL process groups for tp/pp/dp; here the same
    topology is a single `jax.sharding.Mesh` with axes (data, stage, model)
    and parallelism is expressed as sharding over those axes.
    """

    data_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    tensor_parallel_size: int = 1
    # Context parallelism: the sequence axis sharded over the `context`
    # mesh axis, exact ring attention at every layer
    # (parallel/ring_attention.py). BEYOND-reference capability — the
    # reference's only long-sequence lever is SP + selective recompute
    # (ref: transformer.py:508-523); cp shards the N^2 attention itself.
    context_parallel_size: int = 1
    # NOTE deliberately absent: virtual/interleaved pipeline
    # (ref: --num_layers_per_virtual_pipeline_stage arguments.py:828).
    # vpp exists to shrink the pipeline bubble when 1F1B's memory
    # (∝ pp in-flight full-chunk stashes) forbids more microbatches. The
    # TPU schedule remats per tick, so per-stage live memory is one
    # boundary (b,s,h) per tick and raising num_microbatches is the
    # bubble lever (see parallel/pipeline.py module docstring).
    # Korthikanti sequence parallelism over the model axis
    # (ref: arguments.py:683; forced off at tp=1 per arguments.py:327-328).
    sequence_parallel: bool = False
    # ZeRO-1 optimizer-state sharding over data axis
    # (ref: --use_distributed_optimizer arguments.py:864). On pure-dp
    # meshes with a GPT-family model the gradient reduction runs the
    # EXPLICIT reduce-scatter/all-gather decomposition
    # (optimizer/zero1.py); mixed meshes keep the GSPMD-spec path.
    use_distributed_optimizer: bool = False
    # Size target (MB of fp32 gradient payload) for the explicit path's
    # reduce-scatter buckets — the analogue of the reference's
    # distributed.py grad-buffer packing. One collective per bucket per
    # microbatch; smaller buckets give the latency-hiding scheduler
    # more overlap slack, larger ones amortize collective launch.
    grad_rs_bucket_mb: float = 4.0
    # Opt-in EQuARX-style int8 gradient reduction (ops/quantization
    # conventions: symmetric RTN, per-chunk fp32 scales, fp32
    # accumulation of dequantized partials). Default OFF: the fp path
    # is bitwise-unchanged; drift is measured in bench extra.zero1, not
    # assumed. Requires use_distributed_optimizer on a pure-dp mesh.
    quantized_grad_reduce: bool = False
    # Collective overlap scheduling (ISSUE 12). Both default OFF: the
    # eager explicit path stays the bitwise oracle.
    # --overlap_grad_reduce: the explicit path's backward runs in layer
    # GROUPS (sized by grad_rs_bucket_mb) and issues each group's
    # psum_scatter the moment its cotangents materialize — group N's
    # collective is consumed only after group N-1's backward is emitted
    # (double-buffered), so the latency-hiding scheduler can overlap
    # comm with the remaining backward compute. Requires the explicit
    # ZeRO-1 path (zero1 on a pure-dp mesh, GPT-family model); the m/v
    # layout follows the grads to a within-layer shard axis
    # (parallel/sharding.py zero1_axis skip_leading).
    overlap_grad_reduce: bool = False
    # --overlap_param_gather: the param reassembly after the sharded
    # Adam update becomes explicit per-bucket all-gathers issued
    # first-needed-first (embedding, then layer groups in forward
    # order), double-buffered like the reduce-scatters, instead of one
    # GSPMD whole-tree constraint. Same explicit-path requirements;
    # composes with either grad-reduce path and with
    # quantized_grad_reduce.
    overlap_param_gather: bool = False
    # --async_pipeline_dispatch (pp>1): decouple the stage-ring ppermute
    # from the lockstep tick — the boundary send for tick T is issued in
    # tick T+1's body, data-independent of that tick's stage compute
    # (double-buffered carry; each hop takes 2 ticks, fill/drain grows
    # to 2(pp-1) ticks). Moves toward the MPMD paper's async
    # point-to-point dispatch while keeping the scan-transpose backward
    # (parallel/pipeline.py).
    async_pipeline_dispatch: bool = False
    # Number of microbatches for pipelining / gradient accumulation.
    num_microbatches: int = 1
    # Pipeline backward rematerialization policy — the memory/FLOP trade
    # 1F1B exists to manage (ref: schedules.py:606-722 trains WITHOUT
    # recomputing stage internals). Speaks the SAME policy vocabulary as
    # ModelConfig.remat_policy (REMAT_POLICIES), applied to the per-tick
    # scan body, plus two legacy aliases:
    #   "tick" (legacy alias of "full", the default): jax.checkpoint every
    #     scan tick; backward keeps only the (b,s,h) boundary carry per
    #     tick and recomputes stage internals (~+1 forward of FLOPs — the
    #     memory-minimal choice);
    #   "selective": save_only_these_names over the named save points
    #     (models/remat.py) — matmul outputs kept, elementwise recomputed;
    #   "dots" (legacy alias of "save_dots"): checkpoint_dots policy; every
    #     matmul output is kept (1F1B-class FLOPs at intermediate memory);
    #   "offload": the selective save set parked in pinned host memory;
    #   "none":  no remat; AD stashes every tick's internals (1F1B-class
    #     FLOPs, highest memory — pick when per-stage HBM allows).
    # Measured FLOPs/memory per policy: docs/PIPELINE_MEMORY.md.
    pipeline_remat: str = "tick"

    def __post_init__(self):
        if self.tensor_parallel_size == 1 and self.sequence_parallel:
            object.__setattr__(self, "sequence_parallel", False)
        if self.pipeline_remat not in REMAT_POLICIES + ("tick", "dots"):
            raise ValueError(
                f"pipeline_remat={self.pipeline_remat!r}: expected one of "
                f"{REMAT_POLICIES + ('tick', 'dots')}"
            )
        if self.grad_rs_bucket_mb <= 0:
            raise ValueError(
                f"grad_rs_bucket_mb={self.grad_rs_bucket_mb}: the "
                f"reduce-scatter bucket size target must be positive"
            )
        if self.quantized_grad_reduce:
            # reject dead/misleading combinations at construction (the
            # recompute-flag pattern above): quantization lives inside
            # the explicit decomposition, which needs zero1 on a
            # pure-dp mesh — anywhere else the flag would silently
            # train full-precision.
            if not self.use_distributed_optimizer:
                raise ValueError(
                    "quantized_grad_reduce requires "
                    "use_distributed_optimizer: the int8 reduction is "
                    "the wire format of the ZeRO-1 reduce-scatter "
                    "(optimizer/zero1.py); without it there is no "
                    "decomposed dp reduction to quantize"
                )
            if (self.tensor_parallel_size > 1
                    or self.pipeline_parallel_size > 1
                    or self.context_parallel_size > 1):
                raise ValueError(
                    "quantized_grad_reduce is only available on pure-dp "
                    "meshes (tp=pp=cp=1): the explicit reduce-scatter "
                    "path runs the fwd/bwd inside a data-manual "
                    "shard_map, which cannot nest inside the tp/pp/cp "
                    "programs on this XLA build (docs/GUIDE.md, 'ZeRO-1 "
                    "distributed optimizer')"
                )
            if self.data_parallel_size <= 1:
                raise ValueError(
                    "quantized_grad_reduce with data_parallel_size=1: "
                    "there is no dp gradient reduction to quantize"
                )
        for flag in ("overlap_grad_reduce", "overlap_param_gather"):
            if not getattr(self, flag):
                continue
            # same construction-time gate as quantized_grad_reduce: the
            # overlap scheduling lives inside the explicit decomposition
            # — anywhere else the flag would silently do nothing.
            if not self.use_distributed_optimizer:
                raise ValueError(
                    f"{flag} requires use_distributed_optimizer: the "
                    f"overlap scheduling reorders the ZeRO-1 explicit "
                    f"reduce-scatter/all-gather decomposition "
                    f"(optimizer/zero1.py); without it there is nothing "
                    f"to schedule")
            if (self.tensor_parallel_size > 1
                    or self.pipeline_parallel_size > 1
                    or self.context_parallel_size > 1):
                raise ValueError(
                    f"{flag} is only available on pure-dp meshes "
                    f"(tp=pp=cp=1): the explicit path runs the fwd/bwd "
                    f"inside a data-manual shard_map, which cannot nest "
                    f"inside the tp/pp/cp programs on this XLA build "
                    f"(docs/GUIDE.md, 'Collective overlap scheduling')")
            if self.data_parallel_size <= 1:
                raise ValueError(
                    f"{flag} with data_parallel_size=1: there is no dp "
                    f"collective to overlap")
        if self.async_pipeline_dispatch and self.pipeline_parallel_size <= 1:
            raise ValueError(
                "async_pipeline_dispatch requires pipeline_parallel_size "
                "> 1: it reschedules the stage-ring ppermute "
                "(parallel/pipeline.py); there is no ring at pp=1")

    @property
    def resolved_pipeline_remat(self) -> str:
        """pipeline_remat with the legacy aliases normalized to the shared
        REMAT_POLICIES vocabulary (tick->full, dots->save_dots)."""
        return {"tick": "full", "dots": "save_dots"}.get(
            self.pipeline_remat, self.pipeline_remat
        )

    @property
    def world_size(self) -> int:
        return (
            self.data_parallel_size
            * self.pipeline_parallel_size
            * self.context_parallel_size
            * self.tensor_parallel_size
        )

    @property
    def mesh_shape(self):
        return (
            self.data_parallel_size,
            self.pipeline_parallel_size,
            self.context_parallel_size,
            self.tensor_parallel_size,
        )


# ---------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TrainConfig:
    """Optimizer / schedule / run-control (ref: arguments.py:579-815)."""

    micro_batch_size: int = 1
    global_batch_size: int = 1
    rampup_batch_size: Optional[tuple] = None  # (start, increment, samples)

    train_iters: Optional[int] = None
    train_samples: Optional[int] = None
    exit_interval: Optional[int] = None
    exit_duration_in_mins: Optional[float] = None
    exit_signal_handler: bool = False
    # sentinel-file termination hook — the TPU analogue of ADLR autoresume
    # (ref: --adlr_autoresume arguments.py + utils.py:117-135): when the
    # file appears, every host checkpoints and exits together.
    autoresume_file: Optional[str] = None
    autoresume_interval: int = 50

    # Optimizer (ref: arguments.py:666, optimizer/__init__.py:64)
    optimizer: str = "adam"  # adam | sgd
    lr: float = 1e-4
    min_lr: float = 0.0
    lr_decay_style: str = "linear"  # constant|linear|cosine|inverse-square-root
    lr_decay_iters: Optional[int] = None
    lr_decay_samples: Optional[int] = None
    lr_warmup_iters: int = 0
    lr_warmup_samples: int = 0
    lr_warmup_fraction: Optional[float] = None
    use_checkpoint_opt_param_scheduler: bool = False
    override_opt_param_scheduler: bool = False

    weight_decay: float = 0.01
    start_weight_decay: Optional[float] = None
    end_weight_decay: Optional[float] = None
    weight_decay_incr_style: str = "constant"  # constant|linear|cosine
    clip_grad: float = 1.0
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    sgd_momentum: float = 0.9

    # Mixed precision (ref: arguments.py:783-815)
    fp16: bool = False
    bf16: bool = True
    loss_scale: Optional[float] = None  # constant scale; None => dynamic if fp16
    initial_loss_scale: float = 2.0**32
    min_loss_scale: float = 1.0
    loss_scale_window: int = 1000
    hysteresis: int = 2

    # Checkpointing (ref: arguments.py:751-779)
    save: Optional[str] = None
    load: Optional[str] = None
    save_interval: Optional[int] = None
    finetune: bool = False
    no_save_optim: bool = False
    no_load_optim: bool = False
    no_load_rng: bool = False
    # Fault tolerance (ISSUE 5, training/checkpointing.py +
    # training/watchdog.py):
    # async_save: interval saves go through the CheckpointManager's
    # orbax-async path — the train loop stalls only for the device→host
    # copy (the `ckpt_blocked_ms` gauge), commits finish on a background
    # thread, wait-at-exit only. --no_async_save restores blocking saves.
    async_save: bool = True
    # retention: keep the newest N COMPLETE checkpoints, GC the rest
    # (never the one being written or the one resume read). None = keep
    # everything.
    keep_latest_n: Optional[int] = None
    # loss watchdog: a step whose loss is non-finite or above
    # median + ksigma * robust-sigma of the recent-loss window is
    # SKIPPED in-step (the fp16 scaler's skip machinery, for bf16 too);
    # ksigma <= 0 disables spike detection (NaN/inf losses still skip).
    loss_watchdog_ksigma: float = 0.0
    loss_watchdog_window: int = 64
    # after this many CONSECUTIVE bad steps, reload the last complete
    # checkpoint and fast-forward the data iterator past the poison
    # window; 0 disables rollback (skip-only).
    spike_rollback_patience: int = 0

    # Logging / eval (ref: arguments.py:477-541, 870-877)
    log_interval: int = 100
    eval_interval: int = 1000
    eval_iters: int = 100
    tensorboard_dir: Optional[str] = None
    # ref: --tensorboard_log_interval/--tensorboard_queue_size and the
    # log_*_to_tensorboard toggles (arguments.py:477-529)
    tensorboard_log_interval: int = 1
    tensorboard_queue_size: int = 1000
    log_timers_to_tensorboard: bool = False
    log_validation_ppl_to_tensorboard: bool = False
    log_memory_to_tensorboard: bool = False
    log_world_size_to_tensorboard: bool = False
    # ref: --timing_log_level/--timing_log_option (arguments.py:493-508)
    timing_log_level: int = 0
    timing_log_option: str = "minmax"
    wandb_logger: bool = False
    wandb_project: Optional[str] = None
    wandb_entity: Optional[str] = None
    wandb_id: Optional[str] = None
    wandb_resume: bool = False
    wandb_api_key: Optional[str] = None
    # ref: --log-params-norm / --log-num-zeros-in-grad (arguments.py:481-487)
    log_params_norm: bool = False
    log_num_zeros_in_grad: bool = False
    # ref: --profile/--profile-step-start/--profile-step-end
    # (arguments.py:531-541, nsys there; jax.profiler trace here)
    profile: bool = False
    profile_step_start: int = 10
    profile_step_end: int = 12
    profile_dir: Optional[str] = None
    # flight-recorder telemetry (ISSUE 13, megatron_llm_tpu/telemetry/):
    # trace_dir enables the host span tracer (Chrome trace-event JSON,
    # exported at the end of train()); the flight recorder is ALWAYS on
    # (bounded event ring, auto-dumped on watchdog rollback + SIGTERM
    # emergency save), dumping into flight_record_dir (default: the
    # --save dir). Telemetry never touches jitted code — telemetry-on
    # steps are bitwise telemetry-off (tests/test_telemetry.py).
    trace_dir: Optional[str] = None
    flight_record_dir: Optional[str] = None
    flight_recorder_size: int = 4096
    # goodput & device-cost accounting (ISSUE 15, docs/GUIDE.md
    # "Goodput & device-cost accounting"): the goodput ledger is
    # ALWAYS on (pure host float adds); device_cost_registry opts into
    # mint-time compiled-cost capture (one extra AOT compile per step
    # specialization) which upgrades the live MFU gauge from analytic
    # to registry FLOPs and adds per-executable roofline gauges;
    # chip_spec overrides chipspec detection ("v5e"/"v5p"/"v4" — the
    # roofline denominators); perf_sentinel_ksigma > 0 arms the
    # step-latency regression sentinel (median+MAD, the watchdog's
    # machinery) with its flight-ring auto-dump.
    device_cost_registry: bool = False
    chip_spec: Optional[str] = None
    perf_sentinel_ksigma: float = 0.0
    perf_sentinel_window: int = 64
    perf_sentinel_patience: int = 8

    seed: int = 1234

    def __post_init__(self):
        assert not (self.fp16 and self.bf16)
        if self.train_iters is not None and self.train_samples is not None:
            raise ValueError("specify train_iters or train_samples, not both")
        # iteration- and sample-based schedules must not mix (ref:
        # validate_args arguments.py:98-130)
        if self.train_samples is not None:
            if self.lr_decay_iters is not None or self.lr_warmup_iters:
                raise ValueError(
                    "sample-based run (--train_samples): use "
                    "--lr_decay_samples/--lr_warmup_samples, not the "
                    "*_iters variants"
                )
        elif self.lr_decay_samples is not None or self.lr_warmup_samples:
            raise ValueError(
                "--lr_decay_samples/--lr_warmup_samples require "
                "--train_samples (iteration-based runs use the *_iters "
                "variants)"
            )


# ---------------------------------------------------------------------------
# Model family presets (ref: llama_model.py:22-30, falcon_model.py:18-29,
# examples/finetune.sh:62-109)
# ---------------------------------------------------------------------------

_LLAMA_SIZES = {
    # size -> (layers, hidden, heads, n_kv, ffn)
    7: (32, 4096, 32, 32, 11008),
    13: (40, 5120, 40, 40, 13824),
    30: (60, 6656, 52, 52, 17920),
    34: (48, 8192, 64, 8, 22016),  # CodeLlama-34B (GQA)
    65: (80, 8192, 64, 64, 22016),
    70: (80, 8192, 64, 8, 28672),  # Llama-2-70B (GQA)
}

_FALCON_SIZES = {
    # size -> (layers, hidden, heads, n_kv, parallel_layernorm)
    7: (32, 4544, 71, 1, False),
    40: (60, 8192, 128, 8, True),
}


def llama_config(
    size_b: int = 7,
    version: int = 2,
    seq_length: int = 4096,
    vocab_size: int = 32000,
    tp: int = 1,
    **overrides,
) -> ModelConfig:
    """Llama-1/2/CodeLlama preset (ref: llama_model.py:10-44).

    Asserts mirrored from the reference: rotary + swiglu + RMSNorm + no bias
    + untied embeddings (ref: llama_model.py:22-30).
    """
    layers, hidden, heads, n_kv, ffn = _LLAMA_SIZES[size_b]
    if version == 1:
        seq_length = min(seq_length, 2048)
    cfg = dict(
        num_layers=layers,
        hidden_size=hidden,
        num_attention_heads=heads,
        num_attention_heads_kv=n_kv,
        ffn_hidden_size=ffn,
        seq_length=seq_length,
        max_position_embeddings=seq_length,
        position_embedding_type="rotary",
        glu_activation="swiglu",
        use_rms_norm=True,
        use_bias=False,
        tie_embed_logits=False,
        layernorm_epsilon=1e-6 if version == 1 else 1e-5,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        init_method_std=0.02,
        # Train through the Pallas flash kernel by default, like the
        # reference trains Llama through FlashAttention-2
        # (ref: transformer.py:508-523); proven to compile under Mosaic on
        # TPU and to beat the XLA path (tests/test_flash_attention.py + bench).
        use_flash_attn=True,
    )
    cfg.update(overrides)
    mc = ModelConfig(**cfg)
    if mc.padded_vocab_size == 0:
        mc = dataclasses.replace(mc, padded_vocab_size=mc.pad_vocab_size(vocab_size, tp))
    return mc


def codellama_config(size_b: int = 7, seq_length: int = 16384, **overrides) -> ModelConfig:
    """CodeLlama: Llama-2 + rope_theta=1e6 + 16k seq (ref: examples/finetune.sh:74-86)."""
    overrides.setdefault("rope_theta", 1e6)
    return llama_config(size_b, version=2, seq_length=seq_length,
                        vocab_size=overrides.pop("vocab_size", 32016), **overrides)


def falcon_config(
    size_b: int = 7,
    seq_length: int = 2048,
    vocab_size: int = 65024,
    tp: int = 1,
    **overrides,
) -> ModelConfig:
    """Falcon preset (ref: falcon_model.py:10-42): rotary + MQA/GQA +
    parallel attention; 40B adds parallel layernorm."""
    layers, hidden, heads, n_kv, pln = _FALCON_SIZES[size_b]
    cfg = dict(
        num_layers=layers,
        hidden_size=hidden,
        num_attention_heads=heads,
        num_attention_heads_kv=n_kv,
        ffn_hidden_size=4 * hidden,
        seq_length=seq_length,
        max_position_embeddings=seq_length,
        position_embedding_type="rotary",
        glu_activation=None,
        hidden_act="gelu",
        use_rms_norm=False,
        use_bias=False,
        parallel_attn=True,
        parallel_layernorm=pln,
        tie_embed_logits=True,
        hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    cfg.update(overrides)
    mc = ModelConfig(**cfg)
    if mc.padded_vocab_size == 0:
        mc = dataclasses.replace(mc, padded_vocab_size=mc.pad_vocab_size(vocab_size, tp))
    return mc


def gpt_config(
    num_layers: int = 12,
    hidden_size: int = 768,
    num_attention_heads: int = 12,
    seq_length: int = 1024,
    vocab_size: int = 50257,
    tp: int = 1,
    **overrides,
) -> ModelConfig:
    """GPT-2/3-style preset (ref: gpt_model.py:45)."""
    cfg = dict(
        num_layers=num_layers,
        hidden_size=hidden_size,
        num_attention_heads=num_attention_heads,
        seq_length=seq_length,
        max_position_embeddings=seq_length,
        position_embedding_type="absolute",
        hidden_act="gelu",
        tie_embed_logits=True,
    )
    cfg.update(overrides)
    mc = ModelConfig(**cfg)
    if mc.padded_vocab_size == 0:
        mc = dataclasses.replace(mc, padded_vocab_size=mc.pad_vocab_size(vocab_size, tp))
    return mc


def bert_config(
    num_layers: int = 12,
    hidden_size: int = 768,
    num_attention_heads: int = 12,
    seq_length: int = 512,
    vocab_size: int = 30522,
    tp: int = 1,
    **overrides,
) -> ModelConfig:
    """BERT preset (ref: bert_model.py:125-176 through the standard
    pre-LN ParallelTransformer): learned positions, tokentypes, gelu,
    biases, binary (SOP) head, tied LM head."""
    cfg = dict(
        num_layers=num_layers,
        hidden_size=hidden_size,
        num_attention_heads=num_attention_heads,
        seq_length=seq_length,
        max_position_embeddings=seq_length,
        position_embedding_type="absolute",
        hidden_act="gelu",
        use_rms_norm=False,
        use_bias=True,
        tie_embed_logits=True,
        num_tokentypes=2,
        add_binary_head=True,
    )
    cfg.update(overrides)
    mc = ModelConfig(**cfg)
    if mc.padded_vocab_size == 0:
        mc = dataclasses.replace(mc, padded_vocab_size=mc.pad_vocab_size(vocab_size, tp))
    return mc


def t5_config(
    num_layers: int = 12,
    hidden_size: int = 768,
    num_attention_heads: int = 12,
    seq_length: int = 512,
    decoder_seq_length: int = 128,
    vocab_size: int = 30522,
    tp: int = 1,
    **overrides,
) -> ModelConfig:
    """T5 preset (ref: t5_model.py:70-120): shared embeddings, learned
    positions, gelu, biases. seq_length is the encoder side; the decoder
    length is a data-pipeline property (ref: --decoder_seq_length)."""
    cfg = dict(
        num_layers=num_layers,
        hidden_size=hidden_size,
        num_attention_heads=num_attention_heads,
        seq_length=seq_length,
        max_position_embeddings=max(seq_length, decoder_seq_length),
        position_embedding_type="absolute",
        hidden_act="gelu",
        use_rms_norm=False,
        use_bias=True,
        tie_embed_logits=True,
    )
    cfg.update(overrides)
    mc = ModelConfig(**cfg)
    if mc.padded_vocab_size == 0:
        mc = dataclasses.replace(mc, padded_vocab_size=mc.pad_vocab_size(vocab_size, tp))
    return mc


def tiny_config(**overrides) -> ModelConfig:
    """Small config for tests."""
    cfg = dict(
        num_layers=2,
        hidden_size=64,
        num_attention_heads=4,
        num_attention_heads_kv=2,
        ffn_hidden_size=128,
        seq_length=64,
        max_position_embeddings=64,
        padded_vocab_size=256,
        position_embedding_type="rotary",
        glu_activation="swiglu",
        use_rms_norm=True,
        use_bias=False,
        tie_embed_logits=False,
        hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    cfg.update(overrides)
    return ModelConfig(**cfg)
