"""LR + weight-decay scheduler (ref: megatron/optimizer_param_scheduler.py).

Same decay styles and semantics: warmup ramp (:78-88), then
constant/linear/cosine/inverse-square-root decay (:89-118); weight decay
ramps constant/linear/cosine by completed samples-or-steps (:53-76); state
dict round-trips for checkpoint resume (:130-228).
"""

from __future__ import annotations

import math
from typing import Optional


class OptimizerParamScheduler:
    def __init__(
        self,
        max_lr: float,
        min_lr: float = 0.0,
        lr_warmup_steps: int = 0,
        lr_decay_steps: Optional[int] = None,
        lr_decay_style: str = "linear",
        start_wd: float = 0.01,
        end_wd: float = 0.01,
        wd_incr_steps: Optional[int] = None,
        wd_incr_style: str = "constant",
        use_checkpoint_opt_param_scheduler: bool = False,
        override_opt_param_scheduler: bool = False,
    ):
        assert max_lr >= min_lr >= 0.0
        assert not (use_checkpoint_opt_param_scheduler and override_opt_param_scheduler)
        self.max_lr = max_lr
        self.min_lr = min_lr
        self.lr_warmup_steps = lr_warmup_steps
        self.lr_decay_steps = lr_decay_steps
        self.lr_decay_style = lr_decay_style
        self.start_wd = start_wd
        self.end_wd = end_wd
        self.wd_incr_steps = wd_incr_steps
        self.wd_incr_style = wd_incr_style
        self.use_checkpoint_opt_param_scheduler = use_checkpoint_opt_param_scheduler
        self.override_opt_param_scheduler = override_opt_param_scheduler
        self.num_steps = 0
        if self.lr_decay_steps is not None:
            assert self.lr_decay_steps > 0
            assert self.lr_warmup_steps < self.lr_decay_steps

    # -- lr (ref: optimizer_param_scheduler.py:78-118) --------------------
    def get_lr(self, step: Optional[int] = None) -> float:
        step = self.num_steps if step is None else step
        if self.lr_warmup_steps > 0 and step <= self.lr_warmup_steps:
            return self.max_lr * step / self.lr_warmup_steps
        if self.lr_decay_style == "constant" or self.lr_decay_steps is None:
            return self.max_lr
        if step > self.lr_decay_steps:
            return self.min_lr
        if self.lr_decay_style == "inverse-square-root":
            warmup = max(self.lr_warmup_steps, 1)
            lr = self.max_lr * math.sqrt(warmup) / math.sqrt(max(step, warmup))
            return max(self.min_lr, lr)
        num = step - self.lr_warmup_steps
        den = self.lr_decay_steps - self.lr_warmup_steps
        frac = num / den
        delta = self.max_lr - self.min_lr
        if self.lr_decay_style == "linear":
            coeff = 1.0 - frac
        elif self.lr_decay_style == "cosine":
            coeff = 0.5 * (math.cos(math.pi * frac) + 1.0)
        else:
            raise ValueError(self.lr_decay_style)
        return self.min_lr + coeff * delta

    # -- wd (ref: optimizer_param_scheduler.py:53-76) ---------------------
    def get_wd(self, step: Optional[int] = None) -> float:
        step = self.num_steps if step is None else step
        if self.wd_incr_style == "constant":
            assert self.start_wd == self.end_wd
            return self.end_wd
        if self.wd_incr_steps is None:
            raise ValueError(
                f"wd_incr_style={self.wd_incr_style!r} requires wd_incr_steps"
            )
        frac = min(step / max(self.wd_incr_steps, 1), 1.0)
        delta = self.end_wd - self.start_wd
        if self.wd_incr_style == "linear":
            coeff = frac
        elif self.wd_incr_style == "cosine":
            coeff = 0.5 * (math.cos(math.pi * (1 - frac)) + 1.0)
        else:
            raise ValueError(self.wd_incr_style)
        return self.start_wd + coeff * delta

    def step(self, increment: int = 1):
        self.num_steps += increment
        return self.get_lr(), self.get_wd()

    # -- checkpoint state (ref: :130-228) ---------------------------------
    def state_dict(self) -> dict:
        return {
            "max_lr": self.max_lr,
            "min_lr": self.min_lr,
            "lr_warmup_steps": self.lr_warmup_steps,
            "lr_decay_steps": self.lr_decay_steps,
            "lr_decay_style": self.lr_decay_style,
            "start_wd": self.start_wd,
            "end_wd": self.end_wd,
            "num_steps": self.num_steps,
        }

    def load_state_dict(self, sd: dict):
        """ref semantics: checkpoint values win unless override is set
        (optimizer_param_scheduler.py:176-228)."""
        if self.override_opt_param_scheduler:
            self.num_steps = 0
            self.step(sd["num_steps"])
            return
        if self.use_checkpoint_opt_param_scheduler:
            for k in ("max_lr", "min_lr", "lr_warmup_steps", "lr_decay_steps",
                      "lr_decay_style", "start_wd", "end_wd"):
                setattr(self, k, sd[k])
        self.num_steps = 0
        self.step(sd["num_steps"])
