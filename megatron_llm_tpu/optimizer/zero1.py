"""ZeRO-1 distributed optimizer: the explicit reduce-scatter/all-gather
decomposition (ISSUE 10).

The sharding SPECS for the dp-sharded optimizer state have existed since
the first multichip PR (parallel/sharding.py zero1_spec /
optimizer_state_specs) — but specs alone only tell GSPMD where the
m/v/master leaves LIVE. Nothing guaranteed the gradient reduction
actually lowered to the reduce-scatter(grads) -> shard-local Adam ->
all-gather(params) decomposition the reference hand-codes
(ref: distrib_optimizer.py:522-610) and the llama7b-v5p64 forecast
assumes; on the CPU audit meshes GSPMD provably emits
all-reduce + dynamic-slice instead (no reduce-scatter op at all).

This module is the explicit path. `make_zero1_grad_fn` wraps the
fwd/bwd in a `shard_map` manual over the WHOLE mesh (legal only when
every non-`data` axis has size 1 — pure-dp meshes, where the dp
gradient reduction is the entire collective story), so each dp rank
computes its LOCAL microbatch gradients and the reduction is issued by
us, not inferred by GSPMD:

- grads are packed into size-targeted BUCKETS (`grad_rs_bucket_mb`,
  the analogue of the reference's distributed.py grad buffers): each
  leaf is moved so its zero1 axis (parallel/sharding.py zero1_axis —
  the ONE divisibility rule) leads, reshaped to (dp, n) so row r IS
  rank r's shard, and concatenated;
- one `lax.psum_scatter` per bucket per microbatch: the reduce-scatter
  is issued as the backward of each microbatch releases its grads, so
  XLA's latency-hiding scheduler can overlap bucket k's collective
  with the next microbatch's compute, and the fp32 grad ACCUMULATOR
  lives sharded (1/dp of the replicated path's accumulation memory);
- leaves with no dp-divisible free axis (norm scales — the documented
  replicated residue of zero1_spec) ride a plain psum, exactly the
  leaves whose optimizer state stays replicated;
- opt-in (`quantized_grad_reduce`), the wire format drops to int8:
  each bucket row is chunk-quantized (symmetric round-to-nearest,
  per-chunk fp32 scales — ops/quantization.quantize_rows, the SAME
  convention as the int8 KV pages), exchanged with `lax.all_to_all`,
  and the dp partials are dequantized and accumulated in fp32
  (EQuARX, PAPERS.md: cheap symmetric scheme + fp32 accumulation).
  ~3.9x less gradient wire traffic; accuracy is MEASURED, not assumed
  (bench extra.zero1 reports >=50-step loss-trajectory drift).

Numerics contract (pinned by tests/test_zero1.py): with quantization
OFF, the explicit path is BITWISE identical to the replicated-Adam
trainer — per-step losses, grad norms, final params and moments — at
dp2/dp4 in fp32 and bf16, with fp16 scaler and loss-watchdog skip
semantics intact. The local loss mirrors the replicated program's
exact op chain (model.loss_terms numerator/denominator, division by
the psum'd denominator AFTER the local numerator reduction), and
psum/psum_scatter accumulate partials in the same rank order, so no
term is rounded differently.

Mixed meshes (tp/pp/cp > 1) keep the GSPMD-spec path: partial-manual
shard_map (auto axes) hard-crashes this XLA build's partitioner, and
pp's train step is its own stage-manual program. There the m/v
sharding still buys the 1/dp state memory and train_step steers the
update shard-wise + gathers params explicitly; on TPU the SPMD
partitioner's reduce-scatter creation applies to the steered
all-reduce+slice, which the CPU audit cannot witness (docs/GUIDE.md
"ZeRO-1 distributed optimizer").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu.parallel.mesh import (
    DATA_AXIS,
    ParallelContext,
    manual_region,
)
from megatron_llm_tpu.parallel.sharding import param_specs, zero1_axis

# quantized-reduction chunk: one fp32 scale per this many gradient
# elements (2 KiB of fp32 wire per scale -> 0.2% scale overhead). Small
# enough that one outlier poisons 512 elements, not a whole bucket row.
QUANT_CHUNK = 512


@dataclass(frozen=True)
class Zero1Plan:
    """The per-leaf reduce-scatter layout + bucket assignment for one
    param tree shape. Built once per train-step trace (pure shape math,
    no arrays held)."""

    dp: int
    # per flat leaf: the axis sharded over `data`, or None (psum residue)
    leaf_axes: Tuple[Optional[int], ...]
    # bucket -> list of flat-leaf indices (only sharded leaves)
    buckets: Tuple[Tuple[int, ...], ...]
    # flat-leaf indices with leaf_axes None
    residue: Tuple[int, ...]
    # per flat leaf: global shape (for the (dp, n) reshape bookkeeping)
    shapes: Tuple[Tuple[int, ...], ...]

    def shard_shape(self, i: int) -> Tuple[int, ...]:
        """Leaf i's per-rank shard shape (full shape for residue)."""
        k = self.leaf_axes[i]
        if k is None:
            return self.shapes[i]
        s = list(self.shapes[i])
        s[k] //= self.dp
        return tuple(s)

    def comm_bytes_per_reduce(self, quantized: bool) -> int:
        """Logical gradient bytes on the dp wire for ONE reduce of the
        full tree (per microbatch): fp32 for buckets + residue, or
        int8 + per-chunk fp32 scales for buckets (residue stays fp32)."""
        import numpy as np

        sharded = sum(int(np.prod(self.shapes[i]))
                      for b in self.buckets for i in b)
        res = sum(int(np.prod(self.shapes[i])) for i in self.residue)
        if not quantized:
            return (sharded + res) * 4
        n_chunks = sum(
            -(-sum(int(np.prod(self.shapes[i])) for i in b)
              // (self.dp * QUANT_CHUNK)) * self.dp
            for b in self.buckets if b
        )
        return sharded * 1 + n_chunks * 4 + res * 4


def build_zero1_plan(cfg, params_tmpl, dp: int,
                     bucket_mb: float = 4.0) -> Zero1Plan:
    """Partition the grad tree into size-targeted reduce-scatter buckets
    (greedy fill in tree-flatten order, like the reference's
    distributed.py buffer packing). `bucket_mb` targets the fp32 bucket
    payload; a leaf larger than the target gets its own bucket."""
    flat, _ = jax.tree.flatten(params_tmpl)
    specs, _ = jax.tree.flatten(param_specs(cfg, params_tmpl),
                                is_leaf=lambda x: isinstance(x, P))
    target = max(int(bucket_mb * (1 << 20)), 1)
    leaf_axes: List[Optional[int]] = []
    buckets: List[List[int]] = []
    residue: List[int] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, (leaf, spec) in enumerate(zip(flat, specs)):
        k = zero1_axis(spec, leaf.shape, dp)
        leaf_axes.append(k)
        if k is None:
            residue.append(i)
            continue
        nbytes = int(leaf.size) * 4  # grads reduce in fp32
        if cur and cur_bytes + nbytes > target:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        if cur_bytes >= target:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return Zero1Plan(
        dp=dp,
        leaf_axes=tuple(leaf_axes),
        buckets=tuple(tuple(b) for b in buckets),
        residue=tuple(residue),
        shapes=tuple(tuple(l.shape) for l in flat),
    )


def zero1_out_specs(plan: Zero1Plan, treedef) -> Any:
    """shard_map out_specs for the reduced grad tree: `data` on each
    leaf's zero1 axis, replicated residue. (Pure-dp meshes only — the
    specs never mention other axes.)"""
    specs = []
    for i, k in enumerate(plan.leaf_axes):
        if k is None:
            specs.append(P())
        else:
            parts = [None] * len(plan.shapes[i])
            parts[k] = DATA_AXIS
            specs.append(P(*parts))
    return jax.tree.unflatten(treedef, specs)


def _to_dp_matrix(g: jnp.ndarray, k: int, dp: int) -> jnp.ndarray:
    """Move the zero1 axis to the front and reshape to (dp, n): row r is
    exactly rank r's contiguous PartitionSpec block of axis k."""
    g = jnp.moveaxis(g, k, 0)
    return g.reshape(dp, -1).astype(jnp.float32)


def _from_shard_row(row: jnp.ndarray, shape: Tuple[int, ...],
                    k: int, dp: int) -> jnp.ndarray:
    """Inverse of _to_dp_matrix for ONE rank's row: reshape to the local
    shard block (axis k divided by dp) and move the axis back."""
    moved = (shape[k] // dp,) + tuple(
        n for i, n in enumerate(shape) if i != k)
    return jnp.moveaxis(row.reshape(moved), 0, k)


def _quantized_bucket_reduce_scatter(mat: jnp.ndarray, dp: int,
                                     axis_name: str = DATA_AXIS
                                     ) -> jnp.ndarray:
    """EQuARX-style int8 reduce-scatter of a (dp, n) bucket matrix of
    LOCAL partials: chunk-quantize each row (symmetric RTN int8,
    per-chunk fp32 scales — the ops/quantization convention), exchange
    row r to rank r with all_to_all (int8 + scales on the wire), then
    dequantize and accumulate the dp partials in fp32. Returns this
    rank's reduced (n,) shard."""
    from megatron_llm_tpu.ops.quantization import quantize_rows

    n = mat.shape[1]
    pad = (-n) % QUANT_CHUNK
    if pad:
        mat = jnp.pad(mat, ((0, 0), (0, pad)))
    nch = mat.shape[1] // QUANT_CHUNK
    data, scale = quantize_rows(mat.reshape(dp, nch, QUANT_CHUNK))
    # tiled all_to_all over axis 0: send row j to rank j, receive every
    # peer's row r (r = this rank) stacked on axis 0 = source rank
    data = jax.lax.all_to_all(data, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)
    scale = jax.lax.all_to_all(scale, axis_name, split_axis=0,
                               concat_axis=0, tiled=True)
    part = data.astype(jnp.float32) * scale[..., None]
    red = jnp.sum(part, axis=0).reshape(-1)  # fp32 accumulation
    return red[:n] if pad else red


def reduce_scatter_grads(grads, plan: Zero1Plan, quantized: bool = False,
                         axis_name: str = DATA_AXIS):
    """Inside a data-manual shard_map body: turn each rank's LOCAL
    partial grad tree into the dp-reduced zero1-sharded tree — one
    reduce-scatter (or quantized all_to_all exchange) per bucket, one
    psum for the replicated residue. Bitwise contract (quantized=False):
    psum_scatter accumulates partials in the same rank order psum does,
    and bucket concatenation is elementwise-transparent, so every
    reduced element equals the replicated all-reduce's."""
    flat, treedef = jax.tree.flatten(grads)
    out: List[Any] = [None] * len(flat)
    dp = plan.dp
    for idx in plan.residue:
        out[idx] = jax.lax.psum(flat[idx].astype(jnp.float32), axis_name)
    for bucket in plan.buckets:
        mats = [_to_dp_matrix(flat[i], plan.leaf_axes[i], dp)
                for i in bucket]
        sizes = [m.shape[1] for m in mats]
        cat = mats[0] if len(mats) == 1 else jnp.concatenate(mats, axis=1)
        if quantized:
            shard = _quantized_bucket_reduce_scatter(cat, dp, axis_name)
        else:
            shard = jax.lax.psum_scatter(
                cat, axis_name, scatter_dimension=0, tiled=True
            ).reshape(-1)
        off = 0
        for i, n in zip(bucket, sizes):
            out[i] = _from_shard_row(
                shard[off:off + n], plan.shapes[i], plan.leaf_axes[i], dp)
            off += n
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# The explicit train-step gradient function
# ---------------------------------------------------------------------------


def explicit_zero1_supported(model, pcfg, ctx: Optional[ParallelContext],
                             batch_builder=None) -> bool:
    """Whether the decomposed shard_map path can serve this run: pure-dp
    mesh (every non-data axis size 1 — partial-manual shard_map is not
    available on this XLA build), dp > 1, and a model exposing
    loss_terms (the GPT family). Everything else keeps the GSPMD-spec
    path."""
    return (
        ctx is not None
        and pcfg.use_distributed_optimizer
        and pcfg.data_parallel_size > 1
        and pcfg.pipeline_parallel_size == 1
        and ctx.tp == 1 and ctx.cp == 1 and ctx.pp == 1
        and ctx.dp == pcfg.data_parallel_size
        and batch_builder is None
        and hasattr(model, "loss_terms")
    )


def make_zero1_grad_fn(model, ctx: ParallelContext, plan: Zero1Plan,
                       num_micro: int, quantized: bool):
    """Returns grad_fn(params, batch, rng, loss_scale) ->
    (zero1-sharded fp32 grads, mean loss) — the explicit decomposition
    of the replicated train step's accumulation loop. Called inside the
    jitted train step; the shard_map is manual over the whole (pure-dp)
    mesh."""
    from megatron_llm_tpu.parallel.mesh import shard_map

    mesh = ctx.mesh
    dp = plan.dp

    def local_micro_loss(params, micro, rng, loss_scale, global_den):
        # mirrors train_step.loss_on_micro's exact op chain: the local
        # numerator over this rank's rows divided by the GLOBAL psum'd
        # denominator gives AD the identical cotangent the replicated
        # backward injects, so the local partials are bitwise the
        # partials GSPMD all-reduces.
        with manual_region(constraint_barriers=True):
            # the whole (pure-dp) mesh is manual inside this body, so
            # shard_activation emits optimization barriers where the
            # replicated program has sharding constraints — mirroring
            # its fusion boundaries is what keeps bf16 rounding (and so
            # the bitwise contract) identical (parallel/mesh.py)
            num, _ = model.loss_terms(
                params, dropout_rng=rng, deterministic=rng is None,
                **micro)
        loss = num / jnp.maximum(global_den, 1.0)
        if loss_scale is not None:
            return loss * loss_scale, num
        return loss, num

    def body(params, batch, rng, loss_scale):
        grad_fn = jax.value_and_grad(local_micro_loss, has_aux=True)

        def one_micro(micro, mrng):
            # the denominator is mask arithmetic only (no forward, no
            # params): psum it up front so the grad target divides by
            # the same global count the replicated loss divides by
            den = model.loss_denominator(**micro)
            global_den = jax.lax.psum(den, DATA_AXIS)
            (_, num), g = grad_fn(params, micro, mrng, loss_scale,
                                  global_den)
            # reported loss: numerator psum'd BEFORE the division, the
            # same order the replicated program reduces it
            loss = jax.lax.psum(num, DATA_AXIS) \
                / jnp.maximum(global_den, 1.0)
            gsh = reduce_scatter_grads(g, plan, quantized=quantized)
            return gsh, loss

        if num_micro == 1:
            micro = jax.tree.map(lambda x: x[0], batch)
            grads, loss = one_micro(micro, rng)
            return grads, loss

        _, treedef = jax.tree.flatten(params)
        shard_zeros = jax.tree.unflatten(treedef, [
            jnp.zeros(plan.shard_shape(i), jnp.float32)
            for i in range(len(plan.shapes))
        ])

        def scan_body(carry, xs):
            acc_g, acc_l = carry
            micro, idx = xs
            mrng = jax.random.fold_in(rng, idx) if rng is not None else None
            gsh, loss = one_micro(micro, mrng)
            acc_g = jax.tree.map(lambda a, b: a + b, acc_g, gsh)
            return (acc_g, acc_l + loss), None

        (grads, loss), _ = jax.lax.scan(
            scan_body, (shard_zeros, jnp.float32(0.0)),
            (batch, jnp.arange(num_micro)))
        grads = jax.tree.map(lambda g: g / num_micro, grads)
        return grads, loss / num_micro

    def grad_fn(params, batch, rng, loss_scale):
        p_specs = jax.tree.map(lambda _: P(), params)
        b_specs = jax.tree.map(lambda _: P(None, DATA_AXIS), batch)
        g_specs = zero1_out_specs(plan, jax.tree.structure(params))
        args = [params, batch]
        in_specs = [p_specs, b_specs]
        # rng / loss_scale enter replicated only when present (a None
        # stays a static Python None inside the body)
        if rng is not None:
            args.append(rng)
            in_specs.append(P())
        if loss_scale is not None:
            args.append(loss_scale)
            in_specs.append(P())

        def wrapped(params, batch, *rest):
            rest = list(rest)
            r = rest.pop(0) if rng is not None else None
            if r is not None:
                # per-rank dropout stream: the mask layout over rows
                # differs from the replicated program's (documented in
                # GUIDE.md — the replicated path draws one mask over the
                # global batch)
                r = jax.random.fold_in(r, jax.lax.axis_index(DATA_AXIS))
            ls = rest.pop(0) if loss_scale is not None else None
            return body(params, batch, r, ls)

        return shard_map(
            wrapped, mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=(g_specs, P()),
            check_rep=False,
        )(*args)

    return grad_fn
