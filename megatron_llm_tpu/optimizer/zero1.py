"""ZeRO-1 distributed optimizer: the explicit reduce-scatter/all-gather
decomposition (ISSUE 10).

The sharding SPECS for the dp-sharded optimizer state have existed since
the first multichip PR (parallel/sharding.py zero1_spec /
optimizer_state_specs) — but specs alone only tell GSPMD where the
m/v/master leaves LIVE. Nothing guaranteed the gradient reduction
actually lowered to the reduce-scatter(grads) -> shard-local Adam ->
all-gather(params) decomposition the reference hand-codes
(ref: distrib_optimizer.py:522-610) and the llama7b-v5p64 forecast
assumes; on the CPU audit meshes GSPMD provably emits
all-reduce + dynamic-slice instead (no reduce-scatter op at all).

This module is the explicit path. `make_zero1_grad_fn` wraps the
fwd/bwd in a `shard_map` manual over the WHOLE mesh (legal only when
every non-`data` axis has size 1 — pure-dp meshes, where the dp
gradient reduction is the entire collective story), so each dp rank
computes its LOCAL microbatch gradients and the reduction is issued by
us, not inferred by GSPMD:

- grads are packed into size-targeted BUCKETS (`grad_rs_bucket_mb`,
  the analogue of the reference's distributed.py grad buffers): each
  leaf is moved so its zero1 axis (parallel/sharding.py zero1_axis —
  the ONE divisibility rule) leads, reshaped to (dp, n) so row r IS
  rank r's shard, and concatenated;
- one `lax.psum_scatter` per bucket per microbatch: the reduce-scatter
  is issued as the backward of each microbatch releases its grads, so
  XLA's latency-hiding scheduler can overlap bucket k's collective
  with the next microbatch's compute, and the fp32 grad ACCUMULATOR
  lives sharded (1/dp of the replicated path's accumulation memory);
- `--overlap_grad_reduce` (ISSUE 12) moves the issue points INSIDE
  each microbatch's backward: the forward runs in layer groups saving
  per-group vjps (model.loss_pieces), the backward walks them
  last-to-first, and each group's bucket collective fires at its group
  boundary and is consumed one group later (OverlapPlan /
  _overlap_one_micro — the double buffer that gives every collective a
  layer group of independent compute). `--overlap_param_gather` makes
  the all-gather leg explicit per-bucket, first-needed-first
  (make_explicit_param_gather). The eager sweep stays the bitwise
  oracle (tests/test_overlap.py);
- leaves with no dp-divisible free axis (norm scales — the documented
  replicated residue of zero1_spec) ride a plain psum, exactly the
  leaves whose optimizer state stays replicated;
- opt-in (`quantized_grad_reduce`), the wire format drops to int8:
  each bucket row is chunk-quantized (symmetric round-to-nearest,
  per-chunk fp32 scales — ops/quantization.quantize_rows, the SAME
  convention as the int8 KV pages), exchanged with `lax.all_to_all`,
  and the dp partials are dequantized and accumulated in fp32
  (EQuARX, PAPERS.md: cheap symmetric scheme + fp32 accumulation).
  ~3.9x less gradient wire traffic; accuracy is MEASURED, not assumed
  (bench extra.zero1 reports >=50-step loss-trajectory drift).

Numerics contract (pinned by tests/test_zero1.py): with quantization
OFF, the explicit path is BITWISE identical to the replicated-Adam
trainer — per-step losses, grad norms, final params and moments — at
dp2/dp4 in fp32 and bf16, with fp16 scaler and loss-watchdog skip
semantics intact. The local loss mirrors the replicated program's
exact op chain (model.loss_terms numerator/denominator, division by
the psum'd denominator AFTER the local numerator reduction), and
psum/psum_scatter accumulate partials in the same rank order, so no
term is rounded differently.

Mixed meshes (tp/pp/cp > 1) keep the GSPMD-spec path: partial-manual
shard_map (auto axes) hard-crashes this XLA build's partitioner, and
pp's train step is its own stage-manual program. There the m/v
sharding still buys the 1/dp state memory and train_step steers the
update shard-wise + gathers params explicitly; on TPU the SPMD
partitioner's reduce-scatter creation applies to the steered
all-reduce+slice, which the CPU audit cannot witness (docs/GUIDE.md
"ZeRO-1 distributed optimizer").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu.parallel.mesh import (
    DATA_AXIS,
    ParallelContext,
    manual_region,
)
from megatron_llm_tpu.parallel.sharding import param_specs, zero1_axis

# quantized-reduction chunk: one fp32 scale per this many gradient
# elements (2 KiB of fp32 wire per scale -> 0.2% scale overhead). Small
# enough that one outlier poisons 512 elements, not a whole bucket row.
QUANT_CHUNK = 512


def _bucket_wire_bytes(elems: int, dp: int, quantized: bool) -> int:
    """Wire bytes for one bucket of `elems` fp32 gradient elements:
    fp32, or int8 payload + one fp32 scale per QUANT_CHUNK chunk per
    rank row (the _quantized_bucket_reduce_scatter format)."""
    if not quantized:
        return elems * 4
    n_chunks = -(-elems // (dp * QUANT_CHUNK)) * dp
    return elems * 1 + n_chunks * 4


@dataclass(frozen=True)
class Zero1Plan:
    """The per-leaf reduce-scatter layout + bucket assignment for one
    param tree shape. Built once per train-step trace (pure shape math,
    no arrays held)."""

    dp: int
    # per flat leaf: the axis sharded over `data`, or None (psum residue)
    leaf_axes: Tuple[Optional[int], ...]
    # bucket -> list of flat-leaf indices (only sharded leaves)
    buckets: Tuple[Tuple[int, ...], ...]
    # flat-leaf indices with leaf_axes None
    residue: Tuple[int, ...]
    # per flat leaf: global shape (for the (dp, n) reshape bookkeeping)
    shapes: Tuple[Tuple[int, ...], ...]

    def shard_shape(self, i: int) -> Tuple[int, ...]:
        """Leaf i's per-rank shard shape (full shape for residue)."""
        k = self.leaf_axes[i]
        if k is None:
            return self.shapes[i]
        s = list(self.shapes[i])
        s[k] //= self.dp
        return tuple(s)

    def bucket_comm_bytes(self, quantized: bool) -> Tuple[int, ...]:
        """Per-bucket wire bytes for ONE reduce (one entry per issue
        point) — what bucket sizing is tuned against the overlap window
        with (step-0 gauge `grad-rs-bucket-bytes`, ISSUE 12)."""
        import numpy as np

        out = []
        for b in self.buckets:
            elems = sum(int(np.prod(self.shapes[i])) for i in b)
            out.append(_bucket_wire_bytes(elems, self.dp, quantized))
        return tuple(out)

    def comm_bytes_per_reduce(self, quantized: bool) -> int:
        """Logical gradient bytes on the dp wire for ONE reduce of the
        full tree (per microbatch): fp32 for buckets + residue, or
        int8 + per-chunk fp32 scales for buckets (residue stays fp32)."""
        import numpy as np

        res = sum(int(np.prod(self.shapes[i])) for i in self.residue)
        return sum(self.bucket_comm_bytes(quantized)) + res * 4


def build_zero1_plan(cfg, params_tmpl, dp: int,
                     bucket_mb: float = 4.0) -> Zero1Plan:
    """Partition the grad tree into size-targeted reduce-scatter buckets
    (greedy fill in tree-flatten order, like the reference's
    distributed.py buffer packing). `bucket_mb` targets the fp32 bucket
    payload; a leaf larger than the target gets its own bucket."""
    flat, _ = jax.tree.flatten(params_tmpl)
    specs, _ = jax.tree.flatten(param_specs(cfg, params_tmpl),
                                is_leaf=lambda x: isinstance(x, P))
    target = max(int(bucket_mb * (1 << 20)), 1)
    leaf_axes: List[Optional[int]] = []
    buckets: List[List[int]] = []
    residue: List[int] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, (leaf, spec) in enumerate(zip(flat, specs)):
        k = zero1_axis(spec, leaf.shape, dp)
        leaf_axes.append(k)
        if k is None:
            residue.append(i)
            continue
        nbytes = int(leaf.size) * 4  # grads reduce in fp32
        if cur and cur_bytes + nbytes > target:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        if cur_bytes >= target:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return Zero1Plan(
        dp=dp,
        leaf_axes=tuple(leaf_axes),
        buckets=tuple(tuple(b) for b in buckets),
        residue=tuple(residue),
        shapes=tuple(tuple(l.shape) for l in flat),
    )


@dataclass(frozen=True)
class OverlapPlan:
    """The backward-interleaved variant of Zero1Plan (ISSUE 12,
    --overlap_grad_reduce): the stacked-layer subtree is cut into
    contiguous layer GROUPS sized so one group's fp32 grads hit the
    `grad_rs_bucket_mb` target, and each group is one reduce-scatter
    ISSUE POINT — its collective fires the moment the group's backward
    releases its cotangents, and is consumed only after the next
    group's backward is emitted (double-buffered).

    Layer leaves shard on a WITHIN-layer axis (zero1_axis skip_leading
    — see parallel/sharding.py for why the layer axis cannot carry the
    shard under per-group scatter); a layer leaf with no dp-divisible
    within-layer axis joins the replicated residue. The non-layer
    leaves (embedding, final norm, lm head) keep the eager plan's
    greedy buckets (`aux`), issued after the embedding's backward —
    the last cotangents to materialize."""

    dp: int
    num_layers: int
    # contiguous (lo, hi) layer ranges, FORWARD order; the backward
    # issues them hi-to-lo
    groups: Tuple[Tuple[int, int], ...]
    # per flat leaf of the "layers" subtree: the within-layer zero1
    # axis, or None (residue); shapes are the FULL stacked shapes
    layer_axes: Tuple[Optional[int], ...]
    layer_shapes: Tuple[Tuple[int, ...], ...]
    # the non-layer subtree's eager plan (greedy buckets + residue)
    aux: Zero1Plan

    def layer_shard_shape(self, i: int) -> Tuple[int, ...]:
        k = self.layer_axes[i]
        if k is None:
            return self.layer_shapes[i]
        s = list(self.layer_shapes[i])
        s[k] //= self.dp
        return tuple(s)

    def _group_elems(self, lo: int, hi: int) -> int:
        import numpy as np

        return sum(
            (hi - lo) * int(np.prod(self.layer_shapes[i][1:]))
            for i, k in enumerate(self.layer_axes) if k is not None)

    def bucket_comm_bytes(self, quantized: bool) -> Tuple[int, ...]:
        """Per-issue-point wire bytes: one entry per layer group
        (forward order) followed by the aux buckets."""
        groups = tuple(
            _bucket_wire_bytes(self._group_elems(lo, hi), self.dp,
                               quantized)
            for lo, hi in self.groups)
        return groups + self.aux.bucket_comm_bytes(quantized)

    def comm_bytes_per_reduce(self, quantized: bool) -> int:
        """Same semantics as Zero1Plan.comm_bytes_per_reduce: one full
        reduce of the tree. The total equals the eager plan's whenever
        the residue sets agree — regrouping moves no bytes."""
        import numpy as np

        res = sum(
            int(np.prod(self.layer_shapes[i]))
            for i, k in enumerate(self.layer_axes) if k is None)
        res += sum(int(np.prod(self.aux.shapes[i]))
                   for i in self.aux.residue)
        return sum(
            _bucket_wire_bytes(self._group_elems(lo, hi), self.dp,
                               quantized)
            for lo, hi in self.groups
        ) + sum(self.aux.bucket_comm_bytes(quantized)) + res * 4


def split_aux_layers(params: dict) -> Tuple[dict, Any]:
    """(non-layer subtree, stacked-layer subtree) of a GPT param dict —
    the split the overlap plan/grad-fn/gather all share."""
    return {k: v for k, v in params.items() if k != "layers"}, \
        params["layers"]


def build_overlap_plan(cfg, params_tmpl, dp: int,
                       bucket_mb: float = 4.0) -> OverlapPlan:
    """Cut the layer stack into reduce-scatter groups of ~`bucket_mb`
    MB of fp32 grads each, and plan the aux subtree with the eager
    greedy packing.

    Groups are AT LEAST 2 LAYERS (the trailing remainder merges into
    its neighbor): a 1-layer group's stack is a trip-count-1 lax.scan,
    which XLA's while-loop simplifier unrolls into straight-line code
    and then re-fuses with its surroundings — FMA formation inside the
    inlined layer differs from the rolled scan body's, and the fp32
    grads drift by last ulps (MEASURED on this CPU backend: 1-layer
    groups break the bitwise-vs-eager contract, >= 2-layer groups — a
    live while op with the IDENTICAL body every schedule compiles —
    keep it)."""
    import numpy as np

    aux_tmpl, layers_tmpl = split_aux_layers(params_tmpl)
    flat_l, _ = jax.tree.flatten(layers_tmpl)
    lspecs, _ = jax.tree.flatten(
        param_specs(cfg, params_tmpl)["layers"],
        is_leaf=lambda x: isinstance(x, P))
    L = int(flat_l[0].shape[0])
    layer_axes: List[Optional[int]] = []
    per_layer_bytes = 0
    for leaf, spec in zip(flat_l, lspecs):
        k = zero1_axis(spec, leaf.shape, dp, skip_leading=True)
        layer_axes.append(k)
        if k is not None:
            per_layer_bytes += int(np.prod(leaf.shape[1:])) * 4
    target = max(int(bucket_mb * (1 << 20)), 1)
    per_group = min(L, max(2, target // max(per_layer_bytes, 1))) \
        if L > 1 else 1
    groups = [
        [lo, min(lo + per_group, L)] for lo in range(0, L, per_group)]
    if len(groups) > 1 and groups[-1][1] - groups[-1][0] < 2:
        groups[-2][1] = groups[-1][1]
        groups.pop()
    groups = tuple(tuple(g) for g in groups)
    return OverlapPlan(
        dp=dp,
        num_layers=L,
        groups=groups,
        layer_axes=tuple(layer_axes),
        layer_shapes=tuple(tuple(l.shape) for l in flat_l),
        aux=build_zero1_plan(cfg, aux_tmpl, dp, bucket_mb=bucket_mb),
    )


def overlap_out_specs(plan: OverlapPlan, params_tmpl) -> Any:
    """shard_map out_specs for the overlap-plan grad tree: `data` on
    each layer leaf's within-layer axis, the aux subtree per its eager
    plan."""
    aux_tmpl, layers_tmpl = split_aux_layers(params_tmpl)
    specs = dict(zero1_out_specs(plan.aux, jax.tree.structure(aux_tmpl)))
    flat_l, td_l = jax.tree.flatten(layers_tmpl)
    out_l = []
    for i, k in enumerate(plan.layer_axes):
        if k is None:
            out_l.append(P())
        else:
            parts = [None] * len(plan.layer_shapes[i])
            parts[k] = DATA_AXIS
            out_l.append(P(*parts))
    specs["layers"] = jax.tree.unflatten(td_l, out_l)
    return specs


def zero1_out_specs(plan: Zero1Plan, treedef) -> Any:
    """shard_map out_specs for the reduced grad tree: `data` on each
    leaf's zero1 axis, replicated residue. (Pure-dp meshes only — the
    specs never mention other axes.)"""
    specs = []
    for i, k in enumerate(plan.leaf_axes):
        if k is None:
            specs.append(P())
        else:
            parts = [None] * len(plan.shapes[i])
            parts[k] = DATA_AXIS
            specs.append(P(*parts))
    return jax.tree.unflatten(treedef, specs)


def _to_dp_matrix(g: jnp.ndarray, k: int, dp: int) -> jnp.ndarray:
    """Move the zero1 axis to the front and reshape to (dp, n): row r is
    exactly rank r's contiguous PartitionSpec block of axis k."""
    g = jnp.moveaxis(g, k, 0)
    return g.reshape(dp, -1).astype(jnp.float32)


def _from_shard_row(row: jnp.ndarray, shape: Tuple[int, ...],
                    k: int, dp: int) -> jnp.ndarray:
    """Inverse of _to_dp_matrix for ONE rank's row: reshape to the local
    shard block (axis k divided by dp) and move the axis back."""
    moved = (shape[k] // dp,) + tuple(
        n for i, n in enumerate(shape) if i != k)
    return jnp.moveaxis(row.reshape(moved), 0, k)


def _from_dp_matrix(mat: jnp.ndarray, shape: Tuple[int, ...],
                    k: int) -> jnp.ndarray:
    """Inverse of _to_dp_matrix for the FULL leaf: a (dp, n) matrix
    whose row r is rank r's axis-k block, reassembled to `shape`."""
    rest = tuple(n for i, n in enumerate(shape) if i != k)
    return jnp.moveaxis(mat.reshape((shape[k],) + rest), 0, k)


def _quantized_bucket_reduce_scatter(mat: jnp.ndarray, dp: int,
                                     axis_name: str = DATA_AXIS
                                     ) -> jnp.ndarray:
    """EQuARX-style int8 reduce-scatter of a (dp, n) bucket matrix of
    LOCAL partials: chunk-quantize each row (symmetric RTN int8,
    per-chunk fp32 scales — the ops/quantization convention), exchange
    row r to rank r with all_to_all (int8 + scales on the wire), then
    dequantize and accumulate the dp partials in fp32. Returns this
    rank's reduced (n,) shard."""
    from megatron_llm_tpu.ops.quantization import quantize_rows

    n = mat.shape[1]
    pad = (-n) % QUANT_CHUNK
    if pad:
        mat = jnp.pad(mat, ((0, 0), (0, pad)))
    nch = mat.shape[1] // QUANT_CHUNK
    data, scale = quantize_rows(mat.reshape(dp, nch, QUANT_CHUNK))
    # tiled all_to_all over axis 0: send row j to rank j, receive every
    # peer's row r (r = this rank) stacked on axis 0 = source rank
    data = jax.lax.all_to_all(data, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)
    scale = jax.lax.all_to_all(scale, axis_name, split_axis=0,
                               concat_axis=0, tiled=True)
    part = data.astype(jnp.float32) * scale[..., None]
    red = jnp.sum(part, axis=0).reshape(-1)  # fp32 accumulation
    return red[:n] if pad else red


def reduce_scatter_grads(grads, plan: Zero1Plan, quantized: bool = False,
                         axis_name: str = DATA_AXIS):
    """Inside a data-manual shard_map body: turn each rank's LOCAL
    partial grad tree into the dp-reduced zero1-sharded tree — one
    reduce-scatter (or quantized all_to_all exchange) per bucket, one
    psum for the replicated residue. Bitwise contract (quantized=False):
    psum_scatter accumulates partials in the same rank order psum does,
    and bucket concatenation is elementwise-transparent, so every
    reduced element equals the replicated all-reduce's."""
    flat, treedef = jax.tree.flatten(grads)
    out: List[Any] = [None] * len(flat)
    dp = plan.dp
    for idx in plan.residue:
        out[idx] = jax.lax.psum(flat[idx].astype(jnp.float32), axis_name)
    for bucket in plan.buckets:
        mats = [_to_dp_matrix(flat[i], plan.leaf_axes[i], dp)
                for i in bucket]
        sizes = [m.shape[1] for m in mats]
        cat = mats[0] if len(mats) == 1 else jnp.concatenate(mats, axis=1)
        if quantized:
            shard = _quantized_bucket_reduce_scatter(cat, dp, axis_name)
        else:
            shard = jax.lax.psum_scatter(
                cat, axis_name, scatter_dimension=0, tiled=True
            ).reshape(-1)
        off = 0
        for i, n in zip(bucket, sizes):
            out[i] = _from_shard_row(
                shard[off:off + n], plan.shapes[i], plan.leaf_axes[i], dp)
            off += n
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# The explicit train-step gradient function
# ---------------------------------------------------------------------------


def explicit_zero1_supported(model, pcfg, ctx: Optional[ParallelContext],
                             batch_builder=None) -> bool:
    """Whether the decomposed shard_map path can serve this run: pure-dp
    mesh (every non-data axis size 1 — partial-manual shard_map is not
    available on this XLA build), dp > 1, and a model exposing
    loss_terms (the GPT family). Everything else keeps the GSPMD-spec
    path."""
    return (
        ctx is not None
        and pcfg.use_distributed_optimizer
        and pcfg.data_parallel_size > 1
        and pcfg.pipeline_parallel_size == 1
        and ctx.tp == 1 and ctx.cp == 1 and ctx.pp == 1
        and ctx.dp == pcfg.data_parallel_size
        and batch_builder is None
        and hasattr(model, "loss_terms")
    )


def _overlap_one_micro(model, plan: OverlapPlan, quantized: bool,
                       params, micro, rng, loss_scale, global_den):
    """One microbatch of the SCHEDULED decomposition (ISSUE 12): the
    forward runs group by group saving each group's vjp, the backward
    walks the groups last-to-first, and each group's bucket collective
    is ISSUED at its group boundary and CONSUMED only after the next
    group's backward has been emitted — the double buffer that leaves
    the latency-hiding scheduler a whole layer group of independent
    compute per collective. The math is the eager path's exactly:
    vjp-by-pieces at the factorization boundaries of model.loss_pieces
    is the same op chain value_and_grad(loss_terms) records, psum/
    psum_scatter accumulate in the same rank order, and tied-embedding
    cotangents merge by one fp add (commutative, so bitwise
    order-blind). fp32 bitwise vs eager is pinned in
    tests/test_overlap.py."""
    dp = plan.dp
    aux_params, layers = split_aux_layers(params)
    with manual_region(constraint_barriers=True):
        # same barrier policy as the eager path: shard_activation sites
        # become fusion barriers mirroring the GSPMD program
        embed_fn, group_fn, head_fn = model.loss_pieces(
            dropout_rng=rng, deterministic=rng is None, **micro)
        hidden, embed_vjp = jax.vjp(embed_fn, aux_params)
        group_vjps = []
        for lo, hi in plan.groups:
            sl = jax.tree.map(lambda x, lo=lo, hi=hi: x[lo:hi], layers)
            hidden, vjp_g = jax.vjp(
                lambda p, h, _lo=lo: group_fn(p, h, _lo), sl, hidden)
            group_vjps.append(vjp_g)

        def scaled_head(a, h):
            # the exact scalar chain the eager local_micro_loss
            # differentiates: num / max(global_den, 1) [* loss_scale]
            num, _ = head_fn(a, h)
            loss = num / jnp.maximum(global_den, 1.0)
            if loss_scale is not None:
                loss = loss * loss_scale
            return loss, num

        _, head_vjp, num = jax.vjp(scaled_head, aux_params, hidden,
                                   has_aux=True)

    d_aux, d_h = head_vjp(jnp.float32(1.0))

    G = len(plan.groups)
    group_shards: List[Optional[dict]] = [None] * G
    group_res: List[dict] = [{} for _ in range(G)]
    td_layers_box: List[Any] = [None]

    def issue(gi, d_slice):
        """Pack group gi's sharded-leaf cotangents and fire its
        collective; residue leaves stay local (psum'd once at the
        end)."""
        flat_g, td_layers_box[0] = jax.tree.flatten(d_slice)
        mats, entries = [], []
        for i, g in enumerate(flat_g):
            k = plan.layer_axes[i]
            if k is None:
                group_res[gi][i] = g.astype(jnp.float32)
                continue
            m = _to_dp_matrix(g, k, dp)
            entries.append((i, tuple(g.shape), k, m.shape[1]))
            mats.append(m)
        if not mats:
            # every layer leaf fell to the residue (no within-layer
            # dp-divisible axis at this config) — nothing to scatter
            return gi, None, entries
        cat = mats[0] if len(mats) == 1 else jnp.concatenate(mats, axis=1)
        if quantized:
            sc = _quantized_bucket_reduce_scatter(cat, dp)
        else:
            sc = jax.lax.psum_scatter(
                cat, DATA_AXIS, scatter_dimension=0, tiled=True
            ).reshape(-1)
        return gi, sc, entries

    def consume(pend):
        gi, sc, entries = pend
        out = {}
        if sc is None:
            group_shards[gi] = out
            return
        off = 0
        for i, shp, k, n in entries:
            out[i] = _from_shard_row(sc[off:off + n], shp, k, dp)
            off += n
        group_shards[gi] = out

    pending = None
    for gi in reversed(range(G)):
        d_slice, d_h = group_vjps[gi](d_h)
        issued = issue(gi, d_slice)
        # double buffer: group gi+1's collective is consumed only now,
        # AFTER group gi's backward + issue are in the program — the
        # collective has a group of compute to hide behind
        if pending is not None:
            consume(pending)
        pending = issued
    (d_aux_emb,) = embed_vjp(d_h)
    # tied embeddings: head + embed contributions merge here; fp add is
    # commutative, so the merge order cannot move a bit
    d_aux = jax.tree.map(lambda a, b: a + b, d_aux, d_aux_emb)
    aux_grads = reduce_scatter_grads(d_aux, plan.aux, quantized=quantized)
    consume(pending)

    out_l = []
    for i, k in enumerate(plan.layer_axes):
        if k is None:
            parts = [group_res[g][i] for g in range(G)]
            full = parts[0] if G == 1 else jnp.concatenate(parts, axis=0)
            out_l.append(jax.lax.psum(full, DATA_AXIS))
        else:
            parts = [group_shards[g][i] for g in range(G)]
            out_l.append(
                parts[0] if G == 1 else jnp.concatenate(parts, axis=0))
    grads = dict(aux_grads)
    grads["layers"] = jax.tree.unflatten(td_layers_box[0], out_l)
    loss = jax.lax.psum(num, DATA_AXIS) / jnp.maximum(global_den, 1.0)
    return grads, loss


def make_zero1_grad_fn(model, ctx: ParallelContext, plan,
                       num_micro: int, quantized: bool):
    """Returns grad_fn(params, batch, rng, loss_scale) ->
    (zero1-sharded fp32 grads, mean loss) — the explicit decomposition
    of the replicated train step's accumulation loop. Called inside the
    jitted train step; the shard_map is manual over the whole (pure-dp)
    mesh. `plan` selects the schedule: a Zero1Plan runs the eager
    post-backward sweep (the bitwise oracle), an OverlapPlan the
    backward-interleaved issue points (--overlap_grad_reduce)."""
    from megatron_llm_tpu.parallel.mesh import shard_map

    mesh = ctx.mesh
    dp = plan.dp
    overlap = isinstance(plan, OverlapPlan)

    def local_micro_loss(params, micro, rng, loss_scale, global_den):
        # mirrors train_step.loss_on_micro's exact op chain: the local
        # numerator over this rank's rows divided by the GLOBAL psum'd
        # denominator gives AD the identical cotangent the replicated
        # backward injects, so the local partials are bitwise the
        # partials GSPMD all-reduces.
        with manual_region(constraint_barriers=True):
            # the whole (pure-dp) mesh is manual inside this body, so
            # shard_activation emits optimization barriers where the
            # replicated program has sharding constraints — mirroring
            # its fusion boundaries is what keeps bf16 rounding (and so
            # the bitwise contract) identical (parallel/mesh.py)
            num, _ = model.loss_terms(
                params, dropout_rng=rng, deterministic=rng is None,
                **micro)
        loss = num / jnp.maximum(global_den, 1.0)
        if loss_scale is not None:
            return loss * loss_scale, num
        return loss, num

    def _shard_zeros(params):
        if not overlap:
            _, treedef = jax.tree.flatten(params)
            return jax.tree.unflatten(treedef, [
                jnp.zeros(plan.shard_shape(i), jnp.float32)
                for i in range(len(plan.shapes))
            ])
        aux_t, layers_t = split_aux_layers(params)
        fa, ta = jax.tree.flatten(aux_t)
        out = dict(jax.tree.unflatten(ta, [
            jnp.zeros(plan.aux.shard_shape(i), jnp.float32)
            for i in range(len(fa))
        ]))
        fl, tl = jax.tree.flatten(layers_t)
        out["layers"] = jax.tree.unflatten(tl, [
            jnp.zeros(plan.layer_shard_shape(i), jnp.float32)
            for i in range(len(fl))
        ])
        return out

    def body(params, batch, rng, loss_scale):
        grad_fn = jax.value_and_grad(local_micro_loss, has_aux=True)

        def one_micro(micro, mrng):
            # the denominator is mask arithmetic only (no forward, no
            # params): psum it up front so the grad target divides by
            # the same global count the replicated loss divides by
            den = model.loss_denominator(**micro)
            global_den = jax.lax.psum(den, DATA_AXIS)
            if overlap:
                return _overlap_one_micro(
                    model, plan, quantized, params, micro, mrng,
                    loss_scale, global_den)
            (_, num), g = grad_fn(params, micro, mrng, loss_scale,
                                  global_den)
            # reported loss: numerator psum'd BEFORE the division, the
            # same order the replicated program reduces it
            loss = jax.lax.psum(num, DATA_AXIS) \
                / jnp.maximum(global_den, 1.0)
            gsh = reduce_scatter_grads(g, plan, quantized=quantized)
            return gsh, loss

        if num_micro == 1:
            micro = jax.tree.map(lambda x: x[0], batch)
            grads, loss = one_micro(micro, rng)
            return grads, loss

        shard_zeros = _shard_zeros(params)

        def scan_body(carry, xs):
            acc_g, acc_l = carry
            micro, idx = xs
            mrng = jax.random.fold_in(rng, idx) if rng is not None else None
            gsh, loss = one_micro(micro, mrng)
            acc_g = jax.tree.map(lambda a, b: a + b, acc_g, gsh)
            return (acc_g, acc_l + loss), None

        (grads, loss), _ = jax.lax.scan(
            scan_body, (shard_zeros, jnp.float32(0.0)),
            (batch, jnp.arange(num_micro)))
        grads = jax.tree.map(lambda g: g / num_micro, grads)
        return grads, loss / num_micro

    def grad_fn(params, batch, rng, loss_scale):
        p_specs = jax.tree.map(lambda _: P(), params)
        b_specs = jax.tree.map(lambda _: P(None, DATA_AXIS), batch)
        g_specs = (overlap_out_specs(plan, params) if overlap
                   else zero1_out_specs(plan, jax.tree.structure(params)))
        args = [params, batch]
        in_specs = [p_specs, b_specs]
        # rng / loss_scale enter replicated only when present (a None
        # stays a static Python None inside the body)
        if rng is not None:
            args.append(rng)
            in_specs.append(P())
        if loss_scale is not None:
            args.append(loss_scale)
            in_specs.append(P())

        def wrapped(params, batch, *rest):
            rest = list(rest)
            r = rest.pop(0) if rng is not None else None
            if r is not None:
                # per-rank dropout stream: the mask layout over rows
                # differs from the replicated program's (documented in
                # GUIDE.md — the replicated path draws one mask over the
                # global batch)
                r = jax.random.fold_in(r, jax.lax.axis_index(DATA_AXIS))
            ls = rest.pop(0) if loss_scale is not None else None
            return body(params, batch, r, ls)

        return shard_map(
            wrapped, mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=(g_specs, P()),
            check_rep=False,
        )(*args)

    return grad_fn


# ---------------------------------------------------------------------------
# The explicit param all-gather leg (--overlap_param_gather, ISSUE 12)
# ---------------------------------------------------------------------------


def make_explicit_param_gather(ctx: ParallelContext, plan):
    """Returns gather(new_params) -> replicated params: the all-gather
    leg of the decomposition as EXPLICIT per-bucket collectives instead
    of one GSPMD whole-tree constraint. Gathers are issued
    first-needed-first — the aux buckets (embedding leads the aux flat
    order, and the next forward needs the embedding table before any
    layer) and then the layer buckets in FORWARD order — and each
    bucket's gather is consumed only after the next one is issued
    (double-buffered), so bucket N's wire time hides behind bucket
    N+1's issue and, on TPU, behind whatever the scheduler can pull
    over the `-done`. Pure data movement: bitwise vs the GSPMD
    constraint gather (pinned in tests/test_overlap.py). Works with
    either plan flavor (the bucket units follow the active grad
    layout) and composes with --quantized_grad_reduce (the wire format
    of the REDUCE leg is irrelevant here)."""
    from megatron_llm_tpu.parallel.mesh import shard_map

    mesh = ctx.mesh
    dp = plan.dp
    overlap = isinstance(plan, OverlapPlan)

    def _gather_units(units):
        """units: ordered list of buckets, each a list of
        (tag, full_shape, k, shard_array). One packed all_gather per
        bucket; bucket i is unpacked only after bucket i+1's gather is
        issued. Returns {tag: full array}."""
        results = {}

        def consume(pend):
            unit, g = pend
            off = 0
            for tag, shape, k, a in unit:
                n = int(a.size)
                results[tag] = _from_dp_matrix(
                    g[:, off:off + n], shape, k)
                off += n

        pending = None
        for unit in units:
            rows = [jnp.moveaxis(a, k, 0).reshape(-1)
                    for (_, _, k, a) in unit]
            row = rows[0] if len(rows) == 1 else jnp.concatenate(rows)
            g = jax.lax.all_gather(row, DATA_AXIS, axis=0, tiled=False)
            if pending is not None:
                consume(pending)
            pending = (unit, g)
        if pending is not None:
            consume(pending)
        return results

    def _eager_body(p):
        flat, treedef = jax.tree.flatten(p)
        units = [
            [(i, plan.shapes[i], plan.leaf_axes[i], flat[i])
             for i in bucket]
            for bucket in plan.buckets if bucket
        ]
        results = _gather_units(units)
        out = [results.get(i, flat[i]) for i in range(len(flat))]
        return jax.tree.unflatten(treedef, out)

    def _overlap_body(p):
        aux_t, layers_t = split_aux_layers(p)
        fa, ta = jax.tree.flatten(aux_t)
        fl, tl = jax.tree.flatten(layers_t)
        units = [
            [(("aux", i), plan.aux.shapes[i], plan.aux.leaf_axes[i],
              fa[i]) for i in bucket]
            for bucket in plan.aux.buckets if bucket
        ]
        for gi, (lo, hi) in enumerate(plan.groups):
            unit = []
            for i, k in enumerate(plan.layer_axes):
                if k is None:
                    continue
                shape = (hi - lo,) + plan.layer_shapes[i][1:]
                unit.append((("layer", i, gi), shape, k, fl[i][lo:hi]))
            if unit:
                units.append(unit)
        results = _gather_units(units)
        out_a = [results.get(("aux", i), fa[i]) for i in range(len(fa))]
        out_l = []
        for i, k in enumerate(plan.layer_axes):
            if k is None:
                out_l.append(fl[i])
                continue
            parts = [results[("layer", i, gi)]
                     for gi in range(len(plan.groups))]
            out_l.append(
                parts[0] if len(parts) == 1
                else jnp.concatenate(parts, axis=0))
        out = dict(jax.tree.unflatten(ta, out_a))
        out["layers"] = jax.tree.unflatten(tl, out_l)
        return out

    def gather(new_params):
        in_specs = (
            overlap_out_specs(plan, new_params) if overlap
            else zero1_out_specs(plan, jax.tree.structure(new_params)))
        out_specs = jax.tree.map(lambda _: P(), new_params)
        body = _overlap_body if overlap else _eager_body
        return shard_map(
            body, mesh=mesh, in_specs=(in_specs,), out_specs=out_specs,
            check_rep=False,
        )(new_params)

    return gather
