"""Mixed-precision Adam/SGD with clipping, nan-skip and ZeRO-1 sharding.

Parity targets:
- `MegatronOptimizer` / `MixedPrecisionOptimizer` /
  `Float16OptimizerWithFloat16Params` (ref: optimizer/optimizer.py:58-545):
  fp32 master state, global-norm clipping, count-zeros, inf/nan skip.
- apex FusedAdam (adamw-style decoupled weight decay) and FusedSGD
  (ref: optimizer/__init__.py:3-64).
- Distributed (ZeRO-1) optimizer (ref: optimizer/distrib_optimizer.py):
  expressed as sharding of the m/v/master trees over the `data` axis —
  XLA emits the reduce-scatter(grads)/all-gather(params) the reference
  hand-codes (ref: distrib_optimizer.py:522-610).

Functional design: `init_optimizer_state` builds the state pytree,
`optimizer_step` is a pure function (params, grads, state, lr, wd) ->
(params, state, stats) that jits and shards like everything else.
Params are held in fp32 and cast to the compute dtype inside the model
(same numerics as the reference's bf16-params + fp32-master scheme, one
copy fewer).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import TrainConfig


class OptimizerState(NamedTuple):
    step: jnp.ndarray  # int32 scalar
    m: Any  # first moment (adam) or momentum buffer (sgd); params-shaped
    v: Optional[Any]  # second moment (adam) or None (sgd)


def _tree_cast(tree, dtype):
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def global_grad_norm(grads) -> jnp.ndarray:
    """L2 norm over the full grad pytree in fp32
    (ref: clip_grad_norm_fp32 optimizer/clip_grads.py:16-107; the
    model-parallel allreduce of partial norms is implicit — sharded leaves
    psum under GSPMD)."""
    leaves = jax.tree.leaves(grads)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    )


def count_zeros(grads) -> jnp.ndarray:
    """ref: count_zeros_fp32 (optimizer/clip_grads.py:110-150)."""
    leaves = jax.tree.leaves(grads)
    return sum(jnp.sum(g == 0.0) for g in leaves)


def init_optimizer_state(params, tcfg: TrainConfig) -> OptimizerState:
    zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    if tcfg.optimizer == "adam":
        return OptimizerState(
            step=jnp.zeros((), jnp.int32),
            m=zeros,
            v=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
        )
    elif tcfg.optimizer == "sgd":
        return OptimizerState(step=jnp.zeros((), jnp.int32), m=zeros, v=None)
    raise ValueError(f"unknown optimizer {tcfg.optimizer}")


def optimizer_step(
    params,
    grads,
    state: OptimizerState,
    tcfg: TrainConfig,
    lr: jnp.ndarray,
    weight_decay: Optional[jnp.ndarray] = None,
    found_inf: Optional[jnp.ndarray] = None,
) -> Tuple[Any, OptimizerState, dict]:
    """One update. Mirrors MixedPrecisionOptimizer.step
    (ref: optimizer.py:407-466): unscaled fp32 grads in, global inf/nan
    check, clip by global norm, adamw/sgd update, skipped iteration leaves
    params+state untouched (ref: optimizer.py:418-432).
    """
    wd = tcfg.weight_decay if weight_decay is None else weight_decay
    grads = _tree_cast(grads, jnp.float32)

    grad_norm = global_grad_norm(grads)
    finite = jnp.isfinite(grad_norm)
    if found_inf is not None:
        finite = finite & ~found_inf

    # clip (ref: clip_grads.py:83-107)
    if tcfg.clip_grad > 0.0:
        clip_coeff = jnp.minimum(tcfg.clip_grad / (grad_norm + 1e-6), 1.0)
        grads = jax.tree.map(lambda g: g * clip_coeff, grads)

    step = state.step + 1

    if tcfg.optimizer == "adam":
        b1, b2, eps = tcfg.adam_beta1, tcfg.adam_beta2, tcfg.adam_eps
        bc1 = 1.0 - b1 ** step.astype(jnp.float32)
        bc2 = 1.0 - b2 ** step.astype(jnp.float32)

        new_m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.m, grads)
        new_v = jax.tree.map(
            lambda v, g: b2 * v + (1 - b2) * jnp.square(g), state.v, grads
        )

        def upd(p, m, v):
            # adamw: decoupled weight decay (apex FusedAdam adam_w_mode)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            p32 = p.astype(jnp.float32)
            return (p32 - lr * (u + wd * p32)).astype(p.dtype)

        new_params = jax.tree.map(upd, params, new_m, new_v)
        new_state = OptimizerState(step=step, m=new_m, v=new_v)
    else:  # sgd with momentum
        mom = tcfg.sgd_momentum

        def upd_buf(b, g, p):
            return mom * b + g + wd * p.astype(jnp.float32)

        new_m = jax.tree.map(upd_buf, state.m, grads, params)
        new_params = jax.tree.map(
            lambda p, b: (p.astype(jnp.float32) - lr * b).astype(p.dtype),
            params,
            new_m,
        )
        new_state = OptimizerState(step=step, m=new_m, v=state.v)

    # skipped iteration on inf/nan (ref: optimizer.py:418-432)
    select = lambda new, old: jax.tree.map(
        lambda n, o: jnp.where(finite, n, o), new, old
    )
    new_params = select(new_params, params)
    new_state = OptimizerState(
        step=jnp.where(finite, step, state.step),
        m=select(new_state.m, state.m),
        v=select(new_state.v, state.v) if state.v is not None else None,
    )

    stats = {
        "grad_norm": grad_norm,
        "skipped": (~finite).astype(jnp.int32),
    }
    return new_params, new_state, stats


def get_optimizer(tcfg: TrainConfig):
    """Convenience pair (ref: get_megatron_optimizer optimizer/__init__.py:64)."""
    return (
        lambda params: init_optimizer_state(params, tcfg),
        lambda params, grads, state, lr, **kw: optimizer_step(
            params, grads, state, tcfg, lr, **kw
        ),
    )
