"""Mixed-precision Adam/SGD with clipping, nan-skip and ZeRO-1 sharding.

Parity targets:
- `MegatronOptimizer` / `MixedPrecisionOptimizer` /
  `Float16OptimizerWithFloat16Params` (ref: optimizer/optimizer.py:58-545):
  fp32 master state, global-norm clipping, count-zeros, inf/nan skip.
- apex FusedAdam (adamw-style decoupled weight decay) and FusedSGD
  (ref: optimizer/__init__.py:3-64).
- Distributed (ZeRO-1) optimizer (ref: optimizer/distrib_optimizer.py):
  expressed as sharding of the m/v/master trees over the `data` axis —
  XLA emits the reduce-scatter(grads)/all-gather(params) the reference
  hand-codes (ref: distrib_optimizer.py:522-610).

Functional design: `init_optimizer_state` builds the state pytree,
`optimizer_step` is a pure function (params, grads, state, lr, wd) ->
(params, state, stats) that jits and shards like everything else.
Params are held in fp32 and cast to the compute dtype inside the model
(same numerics as the reference's bf16-params + fp32-master scheme, one
copy fewer).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import TrainConfig


class OptimizerState(NamedTuple):
    step: jnp.ndarray  # int32 scalar
    m: Any  # first moment (adam) or momentum buffer (sgd); params-shaped
    v: Optional[Any]  # second moment (adam) or None (sgd)
    # fp16 loss-scaler state ({} / scale+trackers dict); None when not fp16
    # (ref: Float16OptimizerWithFloat16Params.grad_scaler optimizer.py:270)
    scaler: Optional[dict] = None


def _tree_cast(tree, dtype):
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def global_grad_norm(grads) -> jnp.ndarray:
    """L2 norm over the full grad pytree in fp32
    (ref: clip_grad_norm_fp32 optimizer/clip_grads.py:16-107; the
    model-parallel allreduce of partial norms is implicit — sharded leaves
    psum under GSPMD)."""
    leaves = jax.tree.leaves(grads)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    )


def count_zeros(grads) -> jnp.ndarray:
    """ref: count_zeros_fp32 (optimizer/clip_grads.py:110-150)."""
    leaves = jax.tree.leaves(grads)
    return sum(jnp.sum(g == 0.0) for g in leaves)


def get_grad_scaler(tcfg: TrainConfig):
    """Scaler for fp16 runs, None otherwise (ref: get_megatron_optimizer
    optimizer/__init__.py:68-92: constant when --loss_scale is set, else
    dynamic)."""
    if not tcfg.fp16:
        return None
    from megatron_llm_tpu.optimizer.grad_scaler import (
        ConstantGradScaler,
        DynamicGradScaler,
    )

    if tcfg.loss_scale is not None:
        return ConstantGradScaler(tcfg.loss_scale)
    return DynamicGradScaler(
        initial_scale=tcfg.initial_loss_scale,
        min_scale=tcfg.min_loss_scale,
        growth_interval=tcfg.loss_scale_window,
        hysteresis=tcfg.hysteresis,
    )


def init_optimizer_state(params, tcfg: TrainConfig) -> OptimizerState:
    zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    scaler = get_grad_scaler(tcfg)
    scaler_state = scaler.init_state() if scaler is not None else None
    if tcfg.optimizer == "adam":
        return OptimizerState(
            step=jnp.zeros((), jnp.int32),
            m=zeros,
            v=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            scaler=scaler_state,
        )
    elif tcfg.optimizer == "sgd":
        return OptimizerState(step=jnp.zeros((), jnp.int32), m=zeros, v=None,
                              scaler=scaler_state)
    raise ValueError(f"unknown optimizer {tcfg.optimizer}")


def optimizer_step(
    params,
    grads,
    state: OptimizerState,
    tcfg: TrainConfig,
    lr: jnp.ndarray,
    weight_decay: Optional[jnp.ndarray] = None,
    found_inf: Optional[jnp.ndarray] = None,
    scaler=None,
) -> Tuple[Any, OptimizerState, dict]:
    """One update. Mirrors MixedPrecisionOptimizer.step
    (ref: optimizer.py:407-466): unscaled fp32 grads in, global inf/nan
    check, clip by global norm, adamw/sgd update, skipped iteration leaves
    params+state untouched (ref: optimizer.py:418-432).

    When `scaler` is passed (fp16), the grads must arrive ALREADY
    unscaled; the overflow check reuses this function's grad norm (an
    overflowed scaled grad is still inf/nan after unscaling, so one norm
    pass serves both the skip and the scaler update — the reference's
    separate _unscale_main_grads_and_check_for_nan pass, optimizer.py:
    340-365, is folded in here). The returned state carries the updated
    scale; stats gains "loss_scale".
    """
    wd = tcfg.weight_decay if weight_decay is None else weight_decay
    grads = _tree_cast(grads, jnp.float32)

    grad_norm = global_grad_norm(grads)
    finite = jnp.isfinite(grad_norm)
    if found_inf is not None:
        # external skip gate (the loss watchdog's spike/NaN flag): skips
        # the UPDATE only. It must not feed the scaler below — a
        # finite-gradient loss spike is not an fp16 overflow, and
        # backing the scale off for it would ratchet toward underflow.
        finite = finite & ~found_inf

    new_scaler_state = state.scaler
    if scaler is not None:
        # the scaler reacts to GENUINE overflow (non-finite grads) only
        new_scaler_state = scaler.update(state.scaler,
                                         ~jnp.isfinite(grad_norm))

    # clip (ref: clip_grads.py:83-107)
    if tcfg.clip_grad > 0.0:
        clip_coeff = jnp.minimum(tcfg.clip_grad / (grad_norm + 1e-6), 1.0)
        grads = jax.tree.map(lambda g: g * clip_coeff, grads)

    step = state.step + 1

    if tcfg.optimizer == "adam":
        b1, b2, eps = tcfg.adam_beta1, tcfg.adam_beta2, tcfg.adam_eps
        bc1 = 1.0 - b1 ** step.astype(jnp.float32)
        bc2 = 1.0 - b2 ** step.astype(jnp.float32)

        new_m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.m, grads)
        new_v = jax.tree.map(
            lambda v, g: b2 * v + (1 - b2) * jnp.square(g), state.v, grads
        )

        def upd(p, m, v):
            # adamw: decoupled weight decay (apex FusedAdam adam_w_mode);
            # 1D params (norm scales, biases) are never decayed
            # (ref: get_param_groups optimizer/__init__.py:28-53)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            p32 = p.astype(jnp.float32)
            wd_p = wd if p.ndim >= 2 else 0.0
            return (p32 - lr * (u + wd_p * p32)).astype(p.dtype)

        new_params = jax.tree.map(upd, params, new_m, new_v)
        new_state = OptimizerState(step=step, m=new_m, v=new_v,
                                   scaler=state.scaler)
    else:  # sgd with momentum
        mom = tcfg.sgd_momentum

        def upd_buf(b, g, p):
            wd_p = wd if p.ndim >= 2 else 0.0
            return mom * b + g + wd_p * p.astype(jnp.float32)

        new_m = jax.tree.map(upd_buf, state.m, grads, params)
        new_params = jax.tree.map(
            lambda p, b: (p.astype(jnp.float32) - lr * b).astype(p.dtype),
            params,
            new_m,
        )
        new_state = OptimizerState(step=step, m=new_m, v=state.v,
                                   scaler=state.scaler)

    # skipped iteration on inf/nan (ref: optimizer.py:418-432)
    select = lambda new, old: jax.tree.map(
        lambda n, o: jnp.where(finite, n, o), new, old
    )
    new_params = select(new_params, params)
    new_state = OptimizerState(
        step=jnp.where(finite, step, state.step),
        m=select(new_state.m, state.m),
        v=select(new_state.v, state.v) if state.v is not None else None,
        scaler=new_scaler_state,
    )

    stats = {
        "grad_norm": grad_norm,
        "skipped": (~finite).astype(jnp.int32),
    }
    if scaler is not None:
        stats["loss_scale"] = scaler.scale(state.scaler)
    # ref training_log field set (training.py:452-626): zeros-in-grad and
    # params L2 norm, computed in-step so they ride the same dispatch
    if tcfg.log_num_zeros_in_grad:
        stats["num_zeros"] = count_zeros(grads)
    if tcfg.log_params_norm:
        stats["params_norm"] = global_grad_norm(new_params)
    return new_params, new_state, stats


def get_optimizer(tcfg: TrainConfig):
    """Convenience pair (ref: get_megatron_optimizer optimizer/__init__.py:64)."""
    return (
        lambda params: init_optimizer_state(params, tcfg),
        lambda params, grads, state, lr, **kw: optimizer_step(
            params, grads, state, tcfg, lr, **kw
        ),
    )
