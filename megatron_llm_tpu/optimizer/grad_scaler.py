"""Loss scaling for fp16 (ref: megatron/optimizer/grad_scaler.py).

bf16 on TPU needs no scaling (SURVEY.md §7 design stance); these exist for
fp16 parity. `DynamicGradScaler` doubles every `growth_interval` clean steps
and halves on overflow with hysteresis (ref: grad_scaler.py:53-125,
args arguments.py:788-798). State is a plain dict so it jits/checkpoints.
"""

from __future__ import annotations

import jax.numpy as jnp


class ConstantGradScaler:
    def __init__(self, scale: float):
        self._scale = jnp.float32(scale)

    def init_state(self) -> dict:
        return {}

    def scale(self, state):
        return self._scale

    def update(self, state, found_inf):
        return state

    def state_dict(self, state):
        return {"scale": float(self._scale)}

    def load_state_dict(self, state, sd):
        self._scale = jnp.float32(sd["scale"])
        return state


class DynamicGradScaler:
    def __init__(
        self,
        initial_scale: float = 2.0**32,
        min_scale: float = 1.0,
        growth_factor: float = 2.0,
        backoff_factor: float = 0.5,
        growth_interval: int = 1000,
        hysteresis: int = 2,
    ):
        assert initial_scale > 0 and min_scale > 0
        assert growth_factor > 1.0 and 0.0 < backoff_factor < 1.0
        self.initial_scale = initial_scale
        self.min_scale = min_scale
        self.growth_factor = growth_factor
        self.backoff_factor = backoff_factor
        self.growth_interval = growth_interval
        self.hysteresis = hysteresis

    def init_state(self) -> dict:
        return {
            "scale": jnp.float32(self.initial_scale),
            "growth_tracker": jnp.int32(0),
            "hysteresis_tracker": jnp.int32(self.hysteresis),
        }

    def scale(self, state):
        return state["scale"]

    def update(self, state, found_inf):
        """Pure-functional form of ref grad_scaler.py:86-106, exactly:
        overflow zeroes the growth tracker and decrements hysteresis; once
        hysteresis <= 0 EVERY further overflow backs the scale off (the
        tracker is NOT reset by backoff); only a growth event —
        `growth_interval` consecutive clean steps — restores hysteresis and
        grows the scale."""
        found_inf = found_inf.astype(bool)
        hyst = jnp.where(
            found_inf, state["hysteresis_tracker"] - 1, state["hysteresis_tracker"]
        )
        backoff = found_inf & (hyst <= 0)
        new_scale = jnp.where(
            backoff,
            jnp.maximum(state["scale"] * self.backoff_factor, self.min_scale),
            state["scale"],
        )
        growth = jnp.where(found_inf, 0, state["growth_tracker"] + 1)
        grow = ~found_inf & (growth == self.growth_interval)
        new_scale = jnp.where(grow, new_scale * self.growth_factor, new_scale)
        growth = jnp.where(grow, 0, growth)
        hyst = jnp.where(grow, jnp.int32(self.hysteresis), hyst)
        return {
            "scale": new_scale,
            "growth_tracker": growth,
            "hysteresis_tracker": hyst,
        }

    def state_dict(self, state):
        return {k: float(v) if k == "scale" else int(v) for k, v in state.items()}

    def load_state_dict(self, state, sd):
        return {
            "scale": jnp.float32(sd["scale"]),
            "growth_tracker": jnp.int32(sd["growth_tracker"]),
            "hysteresis_tracker": jnp.int32(sd["hysteresis_tracker"]),
        }
