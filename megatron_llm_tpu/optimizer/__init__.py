from megatron_llm_tpu.optimizer.optimizer import (  # noqa: F401
    OptimizerState,
    get_optimizer,
    init_optimizer_state,
    optimizer_step,
)
from megatron_llm_tpu.optimizer.scheduler import OptimizerParamScheduler  # noqa: F401
from megatron_llm_tpu.optimizer.grad_scaler import (  # noqa: F401
    ConstantGradScaler,
    DynamicGradScaler,
)
