"""HuggingFace <-> native weight converters (Llama/CodeLlama, Falcon).

Parity targets: ref weights2megatron/weights2megatron.py:80-146
(`llama_to_megatron` grouped-qkv rearrange + per-head RoPE permute via
permute_qkv.py:12-30) and megatron2hf.py:60-93 (`convert_wqkv`/`convert_ffn`
reverse direction). Everything here is plain numpy on host — no torch, no
jax — so the CLI can stream layer by layer without framework overhead.

Layout facts (see models/attention.py, models/transformer.py):

- native fused wqkv is (h, qkv_size) [input-major]; the output dim is the
  reference's grouped layout [group g: q_g0..q_g{qpk-1}, k_g, v_g] — the
  transpose of the reference's (qkv_size, h) torch Linear weight.
- native RoPE is the Meta interleaved-pair convention (models/rope.py); HF
  Llama/Falcon checkpoints use the half-split ("rotate_half") convention,
  so each q/k head's rows are permuted exactly as the reference does
  (permute_qkv.py:15-18): HF [r0..r_{d/2-1}, i0..i_{d/2-1}] <->
  interleaved [r0, i0, r1, i1, ...]. v is never permuted.
- native GLU w1 is (h, 2, ffn) with index 0 = gate, 1 = up (the reference
  packs [up; gate] into one 2*ffn dim, transformer.py:92-102 — we keep the
  pair axis explicit so TP sharding never crosses it).
- vocab padding: native tables may be padded beyond the HF vocab
  (cfg.pad_vocab_size); extra rows/cols are zero-filled on import and
  sliced off on export.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np

Array = np.ndarray
StateDict = Dict[str, Array]


# ---------------------------------------------------------------------------
# Per-head RoPE-convention permutation
# ---------------------------------------------------------------------------


def permute_rope_rows(w: Array, head_dim: int, revert: bool = False) -> Array:
    """Permute the leading (n_heads*head_dim) rows of `w` between the HF
    half-split layout and the interleaved-pair layout, per head.

    revert=False: HF -> interleaved (ref permute_qkv.py:18).
    revert=True:  interleaved -> HF (ref permute_qkv.py:17).
    """
    n = w.shape[0] // head_dim
    heads = w.reshape(n, head_dim, *w.shape[1:])
    if revert:
        # [r0,i0,r1,i1,...] -> [r..., i...]
        out = heads.reshape(n, head_dim // 2, 2, *w.shape[1:]).swapaxes(1, 2)
    else:
        # [r..., i...] -> [r0,i0,...]
        out = heads.reshape(n, 2, head_dim // 2, *w.shape[1:]).swapaxes(1, 2)
    return out.reshape(w.shape)


def build_grouped_qkv(
    wq: Array, wk: Array, wv: Array, head_dim: int, n_heads: int, n_kv: int,
    permute: bool = True,
) -> Array:
    """Interleave per-group [q*qpk, k, v] along dim 0 (out-major), applying
    the RoPE permute to q/k heads (ref: rearrange_qkv
    weights2megatron.py:87-99). Inputs are torch-Linear-layout (out, in)."""
    qpk = n_heads // n_kv
    if permute:
        wq = permute_rope_rows(wq, head_dim)
        wk = permute_rope_rows(wk, head_dim)
    q = wq.reshape(n_kv, qpk, head_dim, -1)
    k = wk.reshape(n_kv, 1, head_dim, -1)
    v = wv.reshape(n_kv, 1, head_dim, -1)
    grouped = np.concatenate([q, k, v], axis=1)  # (n_kv, qpk+2, d, in)
    return grouped.reshape(n_kv * (qpk + 2) * head_dim, -1)


def split_grouped_qkv(
    qkv: Array, head_dim: int, n_heads: int, n_kv: int, permute: bool = True,
):
    """Inverse of build_grouped_qkv (ref: convert_wqkv megatron2hf.py:60-86)."""
    qpk = n_heads // n_kv
    grouped = qkv.reshape(n_kv, qpk + 2, head_dim, -1)
    wq = grouped[:, :qpk].reshape(n_heads * head_dim, -1)
    wk = grouped[:, qpk].reshape(n_kv * head_dim, -1)
    wv = grouped[:, qpk + 1].reshape(n_kv * head_dim, -1)
    if permute:
        wq = permute_rope_rows(wq, head_dim, revert=True)
        wk = permute_rope_rows(wk, head_dim, revert=True)
    return wq, wk, wv


def _pad_rows(w: Array, rows: int) -> Array:
    if w.shape[0] == rows:
        return w
    assert w.shape[0] < rows, (w.shape, rows)
    pad = np.zeros((rows - w.shape[0],) + w.shape[1:], w.dtype)
    return np.concatenate([w, pad], axis=0)


# ---------------------------------------------------------------------------
# Llama
# ---------------------------------------------------------------------------


def hf_llama_to_native(sd: Mapping[str, Array], cfg, dtype=np.float32) -> dict:
    """transformers LlamaForCausalLM state dict -> native params pytree.

    `sd` maps HF names to numpy arrays in torch Linear layout (out, in) —
    a plain dict or a lazy mapping (e.g. safetensors-backed) that loads
    each tensor on first access, so conversion streams layer by layer.
    ref: llama_to_megatron (weights2megatron.py:80-146), source="hf".
    """
    L, d = cfg.num_layers, cfg.head_dim
    n, n_kv = cfg.num_attention_heads, cfg.num_query_groups
    dt = dtype  # fp32 masters by default (optimizer.py design)

    def get(name):
        return np.asarray(sd[name], np.float32)

    cast = lambda x: np.asarray(x, dt)  # cast per layer to keep peak RAM low
    wqkv, wo, w1, w2, in_n, post_n = [], [], [], [], [], []
    for i in range(L):
        p = f"model.layers.{i}"
        qkv = build_grouped_qkv(
            get(f"{p}.self_attn.q_proj.weight"),
            get(f"{p}.self_attn.k_proj.weight"),
            get(f"{p}.self_attn.v_proj.weight"),
            d, n, n_kv,
        )
        wqkv.append(cast(qkv.T))  # (h, qkv_size)
        wo.append(cast(get(f"{p}.self_attn.o_proj.weight").T))  # (heads*d, h)
        gate = get(f"{p}.mlp.gate_proj.weight").T  # (h, ffn)
        up = get(f"{p}.mlp.up_proj.weight").T
        w1.append(cast(np.stack([gate, up], axis=1)))  # (h, 2, ffn)
        w2.append(cast(get(f"{p}.mlp.down_proj.weight").T))  # (ffn, h)
        in_n.append(cast(get(f"{p}.input_layernorm.weight")))
        post_n.append(cast(get(f"{p}.post_attention_layernorm.weight")))

    emb = _pad_rows(get("model.embed_tokens.weight"), cfg.padded_vocab_size)
    head = _pad_rows(get("lm_head.weight"), cfg.padded_vocab_size).T  # (h, V)

    return {
        "embedding": {"word_embeddings": cast(emb)},
        "layers": {
            "input_norm": {"scale": np.stack(in_n)},
            "attention": {"wqkv": np.stack(wqkv), "wo": np.stack(wo)},
            "mlp": {"w1": np.stack(w1), "w2": np.stack(w2)},
            "post_attention_norm": {"scale": np.stack(post_n)},
        },
        "final_norm": {"scale": cast(get("model.norm.weight"))},
        "lm_head": cast(head),
    }


def native_to_hf_llama(params: Mapping, cfg, vocab_size: int = None) -> StateDict:
    """native params -> transformers LlamaForCausalLM state dict
    (ref: write_llama_model megatron2hf.py:93-200)."""
    L, d = cfg.num_layers, cfg.head_dim
    n, n_kv = cfg.num_attention_heads, cfg.num_query_groups
    V = vocab_size or cfg.padded_vocab_size
    npf = lambda x: np.asarray(x, np.float32)

    layers = params["layers"]
    sd: StateDict = {
        "model.embed_tokens.weight": npf(
            params["embedding"]["word_embeddings"]
        )[:V],
        "model.norm.weight": npf(params["final_norm"]["scale"]),
        "lm_head.weight": npf(params["lm_head"]).T[:V],
    }
    for i in range(L):
        p = f"model.layers.{i}"
        wq, wk, wv = split_grouped_qkv(
            npf(layers["attention"]["wqkv"][i]).T, d, n, n_kv
        )
        sd[f"{p}.self_attn.q_proj.weight"] = wq
        sd[f"{p}.self_attn.k_proj.weight"] = wk
        sd[f"{p}.self_attn.v_proj.weight"] = wv
        sd[f"{p}.self_attn.o_proj.weight"] = npf(layers["attention"]["wo"][i]).T
        w1 = npf(layers["mlp"]["w1"][i])  # (h, 2, ffn)
        sd[f"{p}.mlp.gate_proj.weight"] = w1[:, 0].T
        sd[f"{p}.mlp.up_proj.weight"] = w1[:, 1].T
        sd[f"{p}.mlp.down_proj.weight"] = npf(layers["mlp"]["w2"][i]).T
        sd[f"{p}.input_layernorm.weight"] = npf(layers["input_norm"]["scale"][i])
        sd[f"{p}.post_attention_layernorm.weight"] = npf(
            layers["post_attention_norm"]["scale"][i]
        )
    return sd


# ---------------------------------------------------------------------------
# Falcon
# ---------------------------------------------------------------------------


def hf_falcon_to_native(sd: Mapping[str, Array], cfg, dtype=np.float32) -> dict:
    """transformers FalconForCausalLM state dict -> native params.

    HF Falcon already stores qkv fused in the grouped layout
    ([g: q*qpk, k, v] for new_decoder_architecture; [q..., k, v] == one
    group under multi_query) — only the per-head RoPE permute is needed
    (ref: falcon_to_megatron weights2megatron.py:23-79).
    """
    L, d = cfg.num_layers, cfg.head_dim

    def get(name):
        return np.asarray(sd[name], np.float32)

    cast = lambda x: np.asarray(x, dtype)
    wqkv, wo, w1, w2 = [], [], [], []
    in_w, in_b, mlp_w, mlp_b = [], [], [], []
    for i in range(L):
        p = f"transformer.h.{i}"
        qkv = get(f"{p}.self_attention.query_key_value.weight")
        qkv = _permute_falcon_qkv(qkv, cfg)
        wqkv.append(cast(qkv.T))
        wo.append(cast(get(f"{p}.self_attention.dense.weight").T))
        w1.append(cast(get(f"{p}.mlp.dense_h_to_4h.weight").T))
        w2.append(cast(get(f"{p}.mlp.dense_4h_to_h.weight").T))
        if cfg.parallel_layernorm:  # falcon-40b: ln_attn + ln_mlp
            in_w.append(cast(get(f"{p}.ln_attn.weight")))
            in_b.append(cast(get(f"{p}.ln_attn.bias")))
            mlp_w.append(cast(get(f"{p}.ln_mlp.weight")))
            mlp_b.append(cast(get(f"{p}.ln_mlp.bias")))
        else:
            in_w.append(cast(get(f"{p}.input_layernorm.weight")))
            in_b.append(cast(get(f"{p}.input_layernorm.bias")))

    emb = cast(_pad_rows(
        get("transformer.word_embeddings.weight"), cfg.padded_vocab_size
    ))
    layers = {
        "input_norm": {"scale": np.stack(in_w), "bias": np.stack(in_b)},
        "attention": {"wqkv": np.stack(wqkv), "wo": np.stack(wo)},
        "mlp": {"w1": np.stack(w1), "w2": np.stack(w2)},
    }
    if cfg.parallel_layernorm:
        layers["mlp_norm"] = {"scale": np.stack(mlp_w), "bias": np.stack(mlp_b)}
    return {
        "embedding": {"word_embeddings": emb},
        "layers": layers,
        "final_norm": {
            "scale": cast(get("transformer.ln_f.weight")),
            "bias": cast(get("transformer.ln_f.bias")),
        },
    }


def native_to_hf_falcon(params: Mapping, cfg, vocab_size: int = None) -> StateDict:
    """native params -> transformers FalconForCausalLM state dict."""
    L = cfg.num_layers
    V = vocab_size or cfg.padded_vocab_size
    npf = lambda x: np.asarray(x, np.float32)
    layers = params["layers"]
    emb = npf(params["embedding"]["word_embeddings"])[:V]
    sd: StateDict = {
        "transformer.word_embeddings.weight": emb,
        "lm_head.weight": emb,  # tied (ref asserts allclose, w2m.py:41-42)
        "transformer.ln_f.weight": npf(params["final_norm"]["scale"]),
        "transformer.ln_f.bias": npf(params["final_norm"]["bias"]),
    }
    for i in range(L):
        p = f"transformer.h.{i}"
        qkv = npf(layers["attention"]["wqkv"][i]).T
        sd[f"{p}.self_attention.query_key_value.weight"] = _permute_falcon_qkv(
            qkv, cfg, revert=True
        )
        sd[f"{p}.self_attention.dense.weight"] = npf(
            layers["attention"]["wo"][i]
        ).T
        sd[f"{p}.mlp.dense_h_to_4h.weight"] = npf(layers["mlp"]["w1"][i]).T
        sd[f"{p}.mlp.dense_4h_to_h.weight"] = npf(layers["mlp"]["w2"][i]).T
        if cfg.parallel_layernorm:
            sd[f"{p}.ln_attn.weight"] = npf(layers["input_norm"]["scale"][i])
            sd[f"{p}.ln_attn.bias"] = npf(layers["input_norm"]["bias"][i])
            sd[f"{p}.ln_mlp.weight"] = npf(layers["mlp_norm"]["scale"][i])
            sd[f"{p}.ln_mlp.bias"] = npf(layers["mlp_norm"]["bias"][i])
        else:
            sd[f"{p}.input_layernorm.weight"] = npf(
                layers["input_norm"]["scale"][i]
            )
            sd[f"{p}.input_layernorm.bias"] = npf(
                layers["input_norm"]["bias"][i]
            )
    return sd


def _permute_falcon_qkv(qkv: Array, cfg, revert: bool = False) -> Array:
    """RoPE-permute each q and k head inside a fused grouped qkv weight,
    leaving v untouched (ref: permute_qkv.py:22-29 group loop)."""
    d, qpk, n_kv = cfg.head_dim, cfg.q_per_kv, cfg.num_query_groups
    grouped = qkv.reshape(n_kv, qpk + 2, d, -1)
    qk = grouped[:, : qpk + 1].reshape(n_kv * (qpk + 1) * d, -1)
    qk = permute_rope_rows(qk, d, revert=revert).reshape(n_kv, qpk + 1, d, -1)
    out = np.concatenate([qk, grouped[:, qpk + 1 :]], axis=1)
    return out.reshape(qkv.shape)
