from megatron_llm_tpu.convert.hf import (  # noqa: F401
    hf_falcon_to_native,
    hf_llama_to_native,
    native_to_hf_falcon,
    native_to_hf_llama,
)
