"""Reference-Megatron torch checkpoint <-> native converters.

Parity targets: ref weights2megatron/weights2megatron.py:148-232 (`main` —
the on-disk layout it writes: `latest_checkpointed_iteration.txt` +
`<iter>/mp_rank_00/model_optim_rng.pt` holding
{"model": {"language_model": {"embedding", "transformer"[, "lm_head"]}},
"checkpoint_version": 3.0, "args": Namespace, "iteration"}),
megatron2hf.py:60-93 (`convert_wqkv`/`convert_ffn` — the fused-qkv grouping
and the [up; gate] GLU packing) and megatron/checkpointing.py:340-411
(`fix_query_key_value_ordering` — pre-2.0 qkv row-order fixups).

Layout facts:
- The reference's fused qkv rows are ALREADY the grouped layout
  [group g: q_g0..q_g{qpk-1}, k_g, v_g] x head_dim in the Meta interleaved
  RoPE convention (weights2megatron.py:87-99 builds exactly that; HF
  sources are permuted INTO it) — native wqkv is just its transpose.
- GLU dense_h_to_4h packs [up(ffn); gate(ffn)] along dim 0
  (weights2megatron.py:127-131 concatenates [w3, w1]); native w1 is
  (h, 2, ffn) with index 0 = gate, 1 = up.
- tp/pp-sharded reference checkpoints (multiple mp_rank_XX) must be merged
  with the reference's own tools/checkpoint_util.py first — the same
  requirement its megatron2hf.py imposes (":110 assert ... Unshard").

Everything here is numpy on host; torch is only used to (de)serialize the
.pt container.
"""

from __future__ import annotations

import os
from typing import Mapping, Optional, Tuple

import numpy as np

from megatron_llm_tpu.convert.hf import _pad_rows

Array = np.ndarray


# ---------------------------------------------------------------------------
# Pre-2.0 qkv row-order fixups (ref: checkpointing.py:340-411)
# ---------------------------------------------------------------------------


def fix_qkv_ordering(w: Array, version: float, n_heads: int, n_kv: int,
                     head_dim: int) -> Array:
    """Reorder a fused qkv weight (or bias) saved by checkpoint_version
    < 2.0 into the modern [np, 3, hn] row order. Multi-query checkpoints
    are never reordered (ref :391-393)."""
    if version >= 2.0 or n_kv != n_heads:
        return w
    rest = w.shape[1:]
    if version == 0:
        # [3, np, hn] -> [np, 3, hn]
        t = w.reshape(3, n_heads, head_dim, *rest)
        return np.ascontiguousarray(t.swapaxes(0, 1)).reshape(w.shape)
    if version == 1.0:
        # [np, hn, 3] -> [np, 3, hn]
        t = w.reshape(n_heads, head_dim, 3, *rest)
        return np.ascontiguousarray(t.swapaxes(1, 2)).reshape(w.shape)
    raise ValueError(f"invalid checkpoint version {version}")


# ---------------------------------------------------------------------------
# state-dict <-> native tree
# ---------------------------------------------------------------------------


def _detect_naming(transformer_keys) -> Tuple[str, str]:
    """The fork writes ("transformer", "attention"); upstream megatron
    writes ("encoder", "self_attention") (ref: megatron2hf/permute_qkv.py
    update_checkpoint:52-58). Returns (block_key_unused, attn_key)."""
    for k in transformer_keys:
        if ".self_attention." in k:
            return "encoder", "self_attention"
    return "transformer", "attention"


def reference_to_native(language_model: Mapping, cfg, dtype=np.float32,
                        checkpoint_version: float = 3.0) -> dict:
    """{"embedding", "transformer"|"encoder"[, "lm_head"]} (numpy leaves,
    reference names) -> native params pytree."""
    L, d = cfg.num_layers, cfg.head_dim
    n, n_kv = cfg.num_attention_heads, cfg.num_query_groups
    cast = lambda x: np.asarray(x, dtype)  # noqa: E731

    emb_sd = language_model["embedding"]
    trans = (language_model.get("transformer")
             or language_model.get("encoder"))
    _, attn = _detect_naming(trans.keys())
    get = lambda k: np.asarray(trans[k], np.float32)  # noqa: E731
    has = lambda k: k in trans  # noqa: E731

    def fix(w):
        return fix_qkv_ordering(w, checkpoint_version, n, n_kv, d)

    wqkv, wo, w1, w2 = [], [], [], []
    bqkv, bo, b1, b2 = [], [], [], []
    norms: dict = {}

    def add_norm(group, layer_prefix, ref_name):
        if not has(f"{layer_prefix}.{ref_name}.weight"):
            return
        norms.setdefault(group, {"scale": [], "bias": []})
        norms[group]["scale"].append(
            cast(get(f"{layer_prefix}.{ref_name}.weight")))
        if has(f"{layer_prefix}.{ref_name}.bias"):
            norms[group]["bias"].append(
                cast(get(f"{layer_prefix}.{ref_name}.bias")))

    for i in range(L):
        p = f"layers.{i}"
        wqkv.append(cast(fix(get(f"{p}.{attn}.query_key_value.weight")).T))
        wo.append(cast(get(f"{p}.{attn}.dense.weight").T))
        h4 = get(f"{p}.mlp.dense_h_to_4h.weight")  # (2ffn|ffn, h)
        if cfg.glu_activation:
            up, gate = np.split(h4, 2, axis=0)  # ref packs [up; gate]
            w1.append(cast(np.stack([gate.T, up.T], axis=1)))  # (h, 2, ffn)
        else:
            w1.append(cast(h4.T))
        w2.append(cast(get(f"{p}.mlp.dense_4h_to_h.weight").T))
        if has(f"{p}.{attn}.query_key_value.bias"):
            bqkv.append(cast(fix(get(f"{p}.{attn}.query_key_value.bias"))))
            bo.append(cast(get(f"{p}.{attn}.dense.bias")))
            b4 = get(f"{p}.mlp.dense_h_to_4h.bias")
            if cfg.glu_activation:
                up_b, gate_b = np.split(b4, 2, axis=0)
                b1.append(cast(np.stack([gate_b, up_b], axis=0)))
            else:
                b1.append(cast(b4))
            b2.append(cast(get(f"{p}.mlp.dense_4h_to_h.bias")))
        add_norm("input_norm", p, "input_layernorm")
        add_norm("post_attention_norm", p, "post_attention_layernorm")
        add_norm("mlp_norm", p, "mlp_layernorm")

    attn_tree = {"wqkv": np.stack(wqkv), "wo": np.stack(wo)}
    mlp_tree = {"w1": np.stack(w1), "w2": np.stack(w2)}
    if bqkv:
        attn_tree["bqkv"] = np.stack(bqkv)
        attn_tree["bo"] = np.stack(bo)
        mlp_tree["b1"] = np.stack(b1)
        mlp_tree["b2"] = np.stack(b2)
    layers = {"attention": attn_tree, "mlp": mlp_tree}
    for group, vals in norms.items():
        layers[group] = {"scale": np.stack(vals["scale"])}
        if vals["bias"]:
            layers[group]["bias"] = np.stack(vals["bias"])

    final = {"scale": cast(get("final_layernorm.weight"))}
    if has("final_layernorm.bias"):
        final["bias"] = cast(get("final_layernorm.bias"))

    params = {
        "embedding": {
            "word_embeddings": cast(_pad_rows(
                np.asarray(emb_sd["word_embeddings.weight"], np.float32),
                cfg.padded_vocab_size,
            ))
        },
        "layers": layers,
        "final_norm": final,
    }
    if "position_embeddings.weight" in emb_sd:
        params["embedding"]["position_embeddings"] = cast(
            np.asarray(emb_sd["position_embeddings.weight"], np.float32)
        )
    if "lm_head" in language_model and language_model["lm_head"] is not None:
        params["lm_head"] = cast(_pad_rows(
            np.asarray(language_model["lm_head"], np.float32),
            cfg.padded_vocab_size,
        ).T)
    return params


def native_to_reference(params: Mapping, cfg) -> dict:
    """native params pytree -> {"embedding", "transformer"[, "lm_head"]}
    with reference names (the layout weights2megatron.py:225-232 writes)."""
    L = cfg.num_layers
    npf = lambda x: np.asarray(x, np.float32)  # noqa: E731
    layers = params["layers"]

    embedding = {
        "word_embeddings.weight": npf(params["embedding"]["word_embeddings"])
    }
    if "position_embeddings" in params["embedding"]:
        embedding["position_embeddings.weight"] = npf(
            params["embedding"]["position_embeddings"]
        )
    transformer = {
        "final_layernorm.weight": npf(params["final_norm"]["scale"]),
    }
    if "bias" in params["final_norm"]:
        transformer["final_layernorm.bias"] = npf(
            params["final_norm"]["bias"])

    def put_norm(group, layer_prefix, ref_name, i):
        if group not in layers:
            return
        transformer[f"{layer_prefix}.{ref_name}.weight"] = npf(
            layers[group]["scale"][i])
        if "bias" in layers[group]:
            transformer[f"{layer_prefix}.{ref_name}.bias"] = npf(
                layers[group]["bias"][i])

    for i in range(L):
        p = f"layers.{i}"
        transformer[f"{p}.attention.query_key_value.weight"] = npf(
            layers["attention"]["wqkv"][i]).T
        transformer[f"{p}.attention.dense.weight"] = npf(
            layers["attention"]["wo"][i]).T
        w1 = npf(layers["mlp"]["w1"][i])
        if cfg.glu_activation:
            # native (h, 2, ffn), 0=gate 1=up -> ref packed [up; gate]
            transformer[f"{p}.mlp.dense_h_to_4h.weight"] = np.concatenate(
                [w1[:, 1].T, w1[:, 0].T], axis=0)
        else:
            transformer[f"{p}.mlp.dense_h_to_4h.weight"] = w1.T
        transformer[f"{p}.mlp.dense_4h_to_h.weight"] = npf(
            layers["mlp"]["w2"][i]).T
        if "bqkv" in layers["attention"]:
            transformer[f"{p}.attention.query_key_value.bias"] = npf(
                layers["attention"]["bqkv"][i])
            transformer[f"{p}.attention.dense.bias"] = npf(
                layers["attention"]["bo"][i])
            b1 = npf(layers["mlp"]["b1"][i])
            if cfg.glu_activation:
                transformer[f"{p}.mlp.dense_h_to_4h.bias"] = np.concatenate(
                    [b1[1], b1[0]], axis=0)
            else:
                transformer[f"{p}.mlp.dense_h_to_4h.bias"] = b1
            transformer[f"{p}.mlp.dense_4h_to_h.bias"] = npf(
                layers["mlp"]["b2"][i])
        put_norm("input_norm", p, "input_layernorm", i)
        put_norm("post_attention_norm", p, "post_attention_layernorm", i)
        put_norm("mlp_norm", p, "mlp_layernorm", i)

    out = {"embedding": embedding, "transformer": transformer}
    if "lm_head" in params:
        out["lm_head"] = npf(params["lm_head"]).T
    return out


# ---------------------------------------------------------------------------
# .pt container IO (torch only here)
# ---------------------------------------------------------------------------


def reference_args_for_cfg(cfg) -> dict:
    """The args Namespace fields weights2megatron.py:173-224 records."""
    return {
        "num_layers": cfg.num_layers,
        "hidden_size": cfg.hidden_size,
        "num_attention_heads": cfg.num_attention_heads,
        "num_attention_heads_kv": cfg.num_query_groups,
        "ffn_hidden_size": cfg.ffn_hidden_size,
        "padded_vocab_size": cfg.padded_vocab_size,
        "glu_activation": cfg.glu_activation,
        "use_rms_norm": cfg.use_rms_norm,
        "tie_embed_logits": cfg.tie_embed_logits,
        "parallel_attn": cfg.parallel_attn,
        "parallel_layernorm": cfg.parallel_layernorm,
        "position_embedding_type": cfg.position_embedding_type,
        "max_position_embeddings": cfg.max_position_embeddings,
        "seq_length": cfg.seq_length,
        "layernorm_epsilon": cfg.layernorm_epsilon,
        "rope_theta": cfg.rope_theta,
        "tensor_model_parallel_size": 1,
        "pipeline_model_parallel_size": 1,
    }


def config_from_reference_args(args, language_model=None, **overrides):
    """Build a native ModelConfig from the checkpoint's saved args
    Namespace (the import-side `--use_checkpoint_args`). The reference
    args don't record use_bias; when the state dict is provided, bias
    presence is read from it directly (Falcon uses layernorm WITHOUT
    linear biases, so `not use_rms_norm` alone would misinfer)."""
    from megatron_llm_tpu.config import ModelConfig

    g = lambda k, d=None: getattr(args, k, d)  # noqa: E731
    if language_model is not None:
        trans = (language_model.get("transformer")
                 or language_model.get("encoder"))
        use_bias = any(k.endswith(".query_key_value.bias") for k in trans)
    else:
        use_bias = not bool(g("use_rms_norm", False))
    fields = dict(
        num_layers=g("num_layers"),
        hidden_size=g("hidden_size"),
        num_attention_heads=g("num_attention_heads"),
        num_attention_heads_kv=g("num_attention_heads_kv",
                                 g("num_attention_heads")),
        ffn_hidden_size=g("ffn_hidden_size") or 4 * g("hidden_size"),
        padded_vocab_size=g("padded_vocab_size"),
        glu_activation=g("glu_activation"),
        use_rms_norm=bool(g("use_rms_norm", False)),
        tie_embed_logits=bool(g("tie_embed_logits", True)),
        parallel_attn=bool(g("parallel_attn", False)),
        parallel_layernorm=bool(g("parallel_layernorm", False)),
        position_embedding_type=g("position_embedding_type", "rotary"),
        max_position_embeddings=g("max_position_embeddings", 2048),
        seq_length=g("seq_length", 2048),
        layernorm_epsilon=g("layernorm_epsilon", 1e-5),
        rope_theta=g("rope_theta", 10000.0),
        use_bias=use_bias,
    )
    fields.update(overrides)
    return ModelConfig(**fields)


def load_reference_checkpoint(load_dir: str):
    """Read a reference-layout checkpoint directory. Returns
    (language_model with numpy leaves, args Namespace-or-None, version)."""
    import torch

    tracker = os.path.join(load_dir, "latest_checkpointed_iteration.txt")
    with open(tracker) as f:
        it = f.read().strip()
    sub = "release" if it == "release" else f"iter_{int(it):07d}"
    ranks = sorted(
        d for d in os.listdir(os.path.join(load_dir, sub))
        if d.startswith("mp_rank_")
    )
    assert len(ranks) == 1, (
        f"tp/pp-sharded reference checkpoint ({len(ranks)} mp_rank dirs): "
        "merge with the reference's tools/checkpoint_util.py first (its own "
        "converters require the same, ref megatron2hf.py:110)"
    )
    blob = torch.load(
        os.path.join(load_dir, sub, ranks[0], "model_optim_rng.pt"),
        map_location="cpu", weights_only=False,
    )
    lm = blob["model"]["language_model"]

    def to_np(x):
        return (x.float().numpy() if hasattr(x, "numpy") else
                np.asarray(x, np.float32))

    out = {}
    for part, val in lm.items():
        if isinstance(val, dict):
            out[part] = {k: to_np(v) for k, v in val.items()}
        elif val is not None:
            out[part] = to_np(val)
    return out, blob.get("args"), float(blob.get("checkpoint_version", 3.0))


def save_reference_checkpoint(save_dir: str, language_model: dict,
                              args: dict,
                              iteration: Optional[int] = None) -> str:
    """Write the reference on-disk layout (weights2megatron.py:225-232)."""
    import argparse

    import torch

    it_name = "release" if iteration is None else f"iter_{iteration:07d}"
    rank_dir = os.path.join(save_dir, it_name, "mp_rank_00")
    os.makedirs(rank_dir, exist_ok=True)
    with open(os.path.join(save_dir,
                           "latest_checkpointed_iteration.txt"), "w") as f:
        f.write("release" if iteration is None else str(iteration))

    lm = {}
    for part, val in language_model.items():
        if isinstance(val, dict):
            lm[part] = {k: torch.from_numpy(np.array(v, np.float32))
                        for k, v in val.items()}
        else:
            lm[part] = torch.from_numpy(np.array(val, np.float32))
    blob = {
        "iteration": "release" if iteration is None else iteration,
        "model": {"language_model": lm},
        "checkpoint_version": 3.0,
        "args": argparse.Namespace(**args),
    }
    path = os.path.join(rank_dir, "model_optim_rng.pt")
    torch.save(blob, path)
    return path
