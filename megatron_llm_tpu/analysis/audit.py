"""graft-check pass 2: the AOT compile-contract audit.

Every `@compile_contract` declaration (analysis/contracts.py) is a
claim about the COMPILED artifact: how many executables traffic may
mint, which collectives the lowered HLO may contain per mesh shape,
that no host callbacks or fp64 ops appear, and how much compiled temp
memory the entry point may use at the audit reference config. This
module checks those claims the way the pjit-on-TPUv4 and EQuARX papers
treat collective inventories — by lowering and reading the artifact,
not by inferring from source.

Reference configs are TINY (2-layer models, 8-token contexts) and the
meshes are virtual CPU devices, so the whole audit runs in seconds
under `JAX_PLATFORMS=cpu` anywhere. Tiny shapes still pin the
INVENTORY (which collectives, which callbacks, f64 or not) exactly,
and the temp-bytes budgets pin relative regressions: a remat/layout
change that blows up compiled temp memory is visible here long before
a production shape exists.

Entry points audited (the registry's lowerable surface):
- the five engine builders, through `DecodeEngine.audit_entry_points()`
  against the engine's REAL pools — FIVE times: an fp engine, an
  int8-KV + weight-only-int8 engine (ISSUE 9), a telemetry-on engine
  (ISSUE 13: live span tracer + flight recorder around the mint), and
  a cost-registry-on engine (ISSUE 15: mint-time compiled-cost capture
  live; _check_telemetry_parity pins both instrumented engines'
  artifacts identical to the fp engine's — inventory equality, zero
  host callbacks, equal FLOPs — so neither telemetry nor cost capture
  can ever leak into jitted code), all at mesh tag "single"; plus a
  tp2-MESH engine (ISSUE 14: group-sharded pools under pjit/GSPMD)
  whose per-contract "tp2" collective inventories are pinned —
  all-reduce only for the forward steps, zero collectives for the
  shard-local page copy;
- `ops.weight_quant`, the one-shot fp->int8 decode-weight quantizer;
- `train.step` on tp2 AND dp2x2 meshes — the two forecast mesh shapes
  whose collective inventories ROADMAP items 1/2/4 will be verified
  against;
- `generate.tokens`, `realm.chunk_topk`, `ops.flash_attention` on a
  single device.

`api.pp_decode` / `api.pp_score` / `train.pipeline_step` /
`train.eval_step` carry variant-counted contracts but declare
`collectives=None`: their lowering needs a pp mesh plus a stage-sharded
model and is exercised by the pp test suites; the audit still checks
their budget declarations and marker consistency.

jax is imported lazily — importing this module costs nothing.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from megatron_llm_tpu.analysis.contracts import (
    COLLECTIVE_OPS,
    all_contracts,
    get_contract,
    total_live_variants,
)

__all__ = [
    "TargetResult",
    "audit_lowered",
    "audit_repo",
    "check_contract_markers",
    "collectives_in_text",
]

KNOWN_FAILURES_DOC = "KNOWN_FAILURES.md"

# mesh tag -> (dp, tp). "single" is the no-mesh case. Suffixed tags
# ("dp2+zero1", "dp2+zero1-quant", "dp2tp2+zero1") audit the SAME mesh
# with the distributed optimizer's specializations — the suffix selects
# the contract's collective-inventory row, the prefix the mesh shape.
MESH_TAGS: Dict[str, Tuple[int, int]] = {
    "single": (1, 1),
    "tp2": (1, 2),
    "dp2": (2, 1),
    "dp2tp2": (2, 2),
}


def _mesh_shape_for_tag(tag: str) -> Tuple[int, int]:
    return MESH_TAGS[tag.split("+", 1)[0]]


_COLLECTIVE_RE = re.compile(
    r"\b(" + "|".join(re.escape(c) for c in COLLECTIVE_OPS) + r")\b")
_CUSTOM_CALL_RE = re.compile(r'custom_call_target="([^"]+)"')
_F64_RE = re.compile(r"\bf64\[")
_CALLBACK_MARKERS = ("callback", "infeed", "outfeed", "host")


@dataclass
class TargetResult:
    """One (contract, mesh tag) audit verdict."""

    contract: str
    mesh_tag: str
    ok: bool = True
    failures: List[str] = field(default_factory=list)
    facts: Dict[str, Any] = field(default_factory=dict)

    def fail(self, msg: str):
        self.ok = False
        self.failures.append(msg)

    def to_dict(self) -> dict:
        return {
            "contract": self.contract, "mesh": self.mesh_tag,
            "ok": self.ok, "failures": self.failures, "facts": self.facts,
        }


def collectives_in_text(hlo_text: str) -> frozenset:
    return frozenset(_COLLECTIVE_RE.findall(hlo_text))


def _host_callback_targets(hlo_text: str) -> List[str]:
    out = []
    for tgt in set(_CUSTOM_CALL_RE.findall(hlo_text)):
        low = tgt.lower()
        if any(m in low for m in _CALLBACK_MARKERS):
            out.append(tgt)
    for op in ("infeed", "outfeed"):
        if re.search(rf"\b{op}\b", hlo_text):
            out.append(op)
    return sorted(out)


def audit_lowered(name: str, mesh_tag: str, fn, args: tuple,
                  kwargs: Optional[dict] = None) -> TargetResult:
    """Lower+compile one registered entry point and check the compiled
    artifact against its contract: collective inventory for this mesh
    tag, host callbacks, fp64, and the temp-bytes budget."""
    contract = get_contract(name)
    res = TargetResult(contract=name, mesh_tag=mesh_tag)
    compiled = fn.lower(*args, **(kwargs or {})).compile()
    text = compiled.as_text()

    found = collectives_in_text(text)
    res.facts["collectives"] = sorted(found)
    if contract.collectives is not None:
        if mesh_tag not in contract.collectives:
            res.fail(
                f"mesh tag {mesh_tag!r} not declared in the contract's "
                f"collective inventory (declared: "
                f"{sorted(contract.collectives)}) — declare the allowed "
                f"set for this mesh shape")
        else:
            declared = frozenset(contract.collectives[mesh_tag])
            if found != declared:
                res.fail(
                    f"collective inventory mismatch on {mesh_tag}: "
                    f"lowered HLO contains {sorted(found)}, contract "
                    f"declares {sorted(declared)} — an undeclared "
                    f"collective is exactly the regression benchmarks "
                    f"catch late; update the declaration only WITH the "
                    f"change that justifies it")

    callbacks = _host_callback_targets(text)
    res.facts["host_callbacks"] = callbacks
    if callbacks and not contract.allow_host_callbacks:
        res.fail(
            f"host callbacks in lowered HLO: {callbacks} — a device-host "
            f"round trip inside a jitted entry point (allow_host_callbacks"
            f"=True only with justification)")

    has_f64 = bool(_F64_RE.search(text))
    res.facts["f64"] = has_f64
    if has_f64 and not contract.allow_f64:
        res.fail(
            "fp64 ops in lowered HLO: TPUs emulate f64 at a massive "
            "slowdown — an accidental float64 promotion (Python float "
            "into jnp.asarray, np default dtypes) is leaking into the "
            "traced graph")

    # collective-overlap evidence (ISSUE 12): async -start/-done pairs
    # (a measured 0 on this CPU backend — the same parser counts real
    # pairs on TPU) plus the sync-schedule interleaving the overlap
    # specializations are pinned against (_check_overlap_schedule)
    from megatron_llm_tpu.analysis.overlap import collective_overlap_report

    res.facts["overlap"] = collective_overlap_report(text).to_dict()

    # compiled-cost facts (ISSUE 15): the per-contract FLOPs/bytes the
    # `graft_check.py costs` regression gate diffs against its
    # baseline, through the ONE list-vs-dict normalization the
    # CostRegistry's capture path uses (a JAX return-shape change must
    # break both consumers at once, not one silently)
    try:
        from megatron_llm_tpu.telemetry.costs import _analysis_dict

        d = _analysis_dict(compiled.cost_analysis())
        if "flops" in d:
            res.facts["flops"] = int(d["flops"])
        if "bytes accessed" in d:
            res.facts["bytes_accessed"] = int(d["bytes accessed"])
    except Exception:  # noqa: BLE001 — backend without cost analysis
        pass

    try:
        mem = compiled.memory_analysis()
        tmp = int(mem.temp_size_in_bytes)
        res.facts["temp_bytes"] = tmp
        res.facts["args_bytes"] = int(mem.argument_size_in_bytes)
        if contract.tmp_bytes_budget is not None \
                and tmp > contract.tmp_bytes_budget:
            res.fail(
                f"compiled temp memory {tmp} bytes exceeds the declared "
                f"budget {contract.tmp_bytes_budget} at the audit "
                f"reference config — a layout/remat/fusion regression, "
                f"or a budget that must be re-justified")
    except Exception as e:  # platform without memory_analysis
        res.facts["temp_bytes"] = f"unavailable: {e}"

    # summed across ALL owner buckets: the engine contracts' variants
    # live under owner=engine, and a per-owner read here (no owner in
    # scope) would publish a misleading constant 0 in the report
    res.facts["live_variants"] = total_live_variants(name)
    return res


# ---------------------------------------------------------------------------
# Reference targets
# ---------------------------------------------------------------------------


def _tiny_model():
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.config import tiny_config
    from megatron_llm_tpu.models import LlamaModel

    cfg = tiny_config(compute_dtype=jnp.float32, use_decode_attn=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.key(0))
    return model, params


def _audit_engine() -> List[TargetResult]:
    """The engine entry points, lowered against real (tiny) engines —
    one fp engine with chunked prefill AND speculative decoding
    configured so every builder is reachable, and one QUANTIZED engine
    (kv_dtype int8 + weight-only int8 decode matmuls, ISSUE 9) so the
    quantized step programs are audited to the same contract as the fp
    paths (same collective inventory, no host callbacks / fp64, temp
    budgets). Also checks the config-derived bucket budgets stay within
    each contract's declared max_variants — the same helpers
    (horizon_buckets / mixed_width_buckets) the engine passes at mint
    time, so the audit and the runtime cannot drift; kv_dtype is an
    engine-level choice and must never mint extra variants (the two
    engines are two owners with identical bucket budgets)."""
    import tempfile

    from megatron_llm_tpu.inference.engine import (
        DecodeEngine,
        horizon_buckets,
        mixed_width_buckets,
    )
    from megatron_llm_tpu.ops.quantization import weight_quant_fn

    model, params = _tiny_model()
    eng = DecodeEngine(
        model, params, slots=2, page_size=16, max_context=64,
        step_horizon=8, prefill_chunk_tokens=16, spec_decode_k=2,
        vocab_size=256)
    eng_q = DecodeEngine(
        model, params, slots=2, page_size=16, max_context=64,
        step_horizon=8, prefill_chunk_tokens=16, spec_decode_k=2,
        kv_dtype="int8", quantize_weights=True, vocab_size=256)
    # telemetry-on engine (ISSUE 13): live span tracer + flight
    # recorder while the entry points mint and lower — the contract is
    # that the compiled artifacts are IDENTICAL to the telemetry-off
    # engine's (collective inventory, zero host callbacks), checked by
    # _check_telemetry_parity below. Emission is host-side by design;
    # this row exists so a future change that threads telemetry INTO a
    # jitted step fails the audit, not a production trace.
    eng_t = DecodeEngine(
        model, params, slots=2, page_size=16, max_context=64,
        step_horizon=8, prefill_chunk_tokens=16, spec_decode_k=2,
        vocab_size=256, trace_dir=tempfile.mkdtemp(prefix="graft_audit_"))

    results = []
    for name, fn, args in eng.audit_entry_points():
        results.append(audit_lowered(name, "single", fn, args))
    for name, fn, args in eng_q.audit_entry_points():
        res = audit_lowered(name, "single", fn, args)
        res.facts["quantized"] = True  # int8 KV + int8 decode weights
        results.append(res)
    for name, fn, args in eng_t.audit_entry_points():
        with eng_t.tracer.span("audit_lower", contract=name):
            res = audit_lowered(name, "single", fn, args)
        eng_t.recorder.record("audit_lower", contract=name)
        res.facts["telemetry"] = True
        results.append(res)
    # cost-registry-on engine (ISSUE 15): every mint ran the mint-time
    # capture (lower + compile for cost/memory analysis) — the audited
    # artifacts must be IDENTICAL to the plain fp engine's
    # (_check_telemetry_parity pins it), so cost capture can never
    # perturb what traffic runs. The row also proves capture actually
    # happened: a registry that silently captured nothing would make
    # every cost gauge a fiction.
    eng_c = DecodeEngine(
        model, params, slots=2, page_size=16, max_context=64,
        step_horizon=8, prefill_chunk_tokens=16, spec_decode_k=2,
        vocab_size=256, cost_registry=True, chip_spec="v5e")
    for name, fn, args in eng_c.audit_entry_points():
        res = audit_lowered(name, "single", fn, args)
        res.facts["costs"] = True
        res.facts["cost_records"] = eng_c.costs.captures
        if eng_c.costs.captures == 0:
            res.fail(
                "cost_registry engine minted entry points but the "
                "CostRegistry captured no records — the mint-time "
                "capture hook (contracts.add_mint_listener + "
                "engine._capture_cost) is broken")
        results.append(res)
    # tp2-mesh engine (ISSUE 14): the five entry points lowered on a
    # (1,1,1,2) serving mesh against group-sharded pools — the
    # collective inventory each contract declares for "tp2" is pinned
    # here (all-reduce only for the forward steps, ZERO collectives
    # for the shard-local page copy), alongside the same zero-host-
    # callback / no-fp64 / temp-bytes checks as the single-chip and
    # int8 rows. Lowering runs under the engine's mesh_scope: the
    # GSPMD constraints bake at trace time, so what this audits is
    # exactly the program tp traffic runs.
    import jax as _jax

    if len(_jax.devices()) >= 2:
        eng_tp = DecodeEngine(
            model, params, slots=2, page_size=16, max_context=64,
            step_horizon=8, prefill_chunk_tokens=16, spec_decode_k=2,
            vocab_size=256, serving_tp=2)
        with eng_tp.mesh_scope():
            for name, fn, args in eng_tp.audit_entry_points():
                res = audit_lowered(name, "tp2", fn, args)
                res.facts["serving_tp"] = 2
                results.append(res)
    else:
        r = TargetResult(contract="engine.decode_scan", mesh_tag="tp2")
        r.fail("tp2 engine audit needs >= 2 devices — provision "
               "virtual CPU devices (utils/virtual_mesh.py)")
        results.append(r)
    # the one-shot weight quantizer itself (fp decode tree -> weight-
    # only int8): a registered jitted entry point like any other
    fp_layers = model.prepare_decode_params(params)["layers"]
    wq = audit_lowered("ops.weight_quant", "single", weight_quant_fn(),
                       (fp_layers,))
    results.append(wq)

    budgets = {
        "engine.decode_scan": 2 * len(horizon_buckets(eng.step_horizon)),
        "engine.mixed_step":
            2 * len(mixed_width_buckets(eng.prefill_chunk_tokens)),
        "engine.prefill_bucket": eng._PREFILL_CACHE_CAP,
        "engine.spec_verify": 2,
        "engine.page_copy": 1,
        # cross-replica KV hand-off pair (ISSUE 17): ids is a traced
        # fixed-width vector padded to max_pages_per_slot, so like
        # page_copy each side is ONE executable forever
        "engine.page_export": 1,
        "engine.page_import": 1,
        "ops.weight_quant": 1,
    }
    for res in results:
        contract = get_contract(res.contract)
        derived = budgets[res.contract]
        res.facts["config_budget"] = derived
        res.facts["max_variants"] = contract.max_variants
        if contract.max_variants is not None \
                and derived > contract.max_variants:
            res.fail(
                f"config-derived budget {derived} exceeds declared "
                f"max_variants {contract.max_variants}: the pow2 bucket "
                f"math and the contract declaration disagree")
    return results


def _audit_train_config(num_layers: int = 2):
    """The ONE tiny reference config the train.step audits lower —
    shared with _check_zero1_state_bytes so the state-bytes expectation
    is always computed for the model actually audited. The `+overlap`
    rows lower a 4-layer variant: overlap groups have a 2-layer floor
    (optimizer/zero1.py build_overlap_plan — 1-layer groups unroll and
    break the bitwise contract), so 2 layers would collapse to ONE
    group and leave no boundary for the interleave pin to witness."""
    import jax.numpy as jnp

    from megatron_llm_tpu.config import tiny_config

    return tiny_config(
        num_layers=num_layers, hidden_size=64, num_attention_heads=4,
        num_attention_heads_kv=4, ffn_hidden_size=128, seq_length=32,
        max_position_embeddings=32, padded_vocab_size=128,
        params_dtype=jnp.float32, compute_dtype=jnp.float32)


def _audit_train_step(mesh_tag: str) -> TargetResult:
    """Lower the train step for one mesh tag. A `+zero1` /
    `+zero1-quant` suffix turns on the distributed optimizer (and the
    int8 gradient reduction) — the optimizer state is sharded through
    the SAME optimizer_state_specs path the trainer uses, so the
    audited args bytes are the production layout's."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from megatron_llm_tpu.config import ParallelConfig, TrainConfig
    from megatron_llm_tpu.models import LlamaModel
    from megatron_llm_tpu.optimizer.optimizer import (
        OptimizerState,
        init_optimizer_state,
    )
    from megatron_llm_tpu.parallel.mesh import (
        destroy_parallel,
        initialize_parallel,
    )
    from megatron_llm_tpu.parallel.sharding import (
        optimizer_state_specs,
        param_specs,
    )
    from megatron_llm_tpu.training.train_step import make_train_step

    dp, tp = _mesh_shape_for_tag(mesh_tag)
    zero1 = "+zero1" in mesh_tag
    quant = "-quant" in mesh_tag
    overlap = "+overlap" in mesh_tag
    # "+telemetry" (ISSUE 13): the SAME build as the base tag, but the
    # specialization mints and lowers with a live span tracer + flight
    # recorder around it — exactly the trainer's instrumentation. The
    # artifact must be identical to the base row's
    # (_check_telemetry_parity); telemetry is host-side by contract.
    telemetry = "+telemetry" in mesh_tag
    # "+costs" (ISSUE 15): the same build minted with a live attached
    # CostRegistry capturing the step's compiled cost — exactly the
    # trainer's --device_cost_registry instrumentation; same parity
    # contract as +telemetry.
    costs = "+costs" in mesh_tag
    cfg = _audit_train_config(num_layers=4 if overlap else 2)
    model = LlamaModel(cfg)
    ctx = initialize_parallel(dp=dp, pp=1, tp=tp)
    try:
        mesh = ctx.mesh
        tmpl = jax.eval_shape(model.init, jax.random.key(0))
        pspecs = param_specs(cfg, tmpl)
        psh = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs,
                           is_leaf=lambda x: isinstance(x, P))
        params = jax.jit(model.init, out_shardings=psh)(jax.random.key(0))
        # overlap rows lower the PRODUCTION shape of the schedule: >1
        # microbatch (the per-microbatch issue points live in the scan
        # body, where the scheduler demonstrably interleaves them — in
        # a single-microbatch entry computation the CPU list scheduler
        # is free to sink the collectives into a clump, which says
        # nothing about the dataflow the TPU scheduler overlaps) and a
        # bucket target small enough that the 4-layer model splits into
        # >1 layer group (one group would leave no boundary for the
        # interleave pin in _check_overlap_schedule to witness).
        num_micro = 2 if overlap else 1
        tcfg = TrainConfig(micro_batch_size=2,
                           global_batch_size=num_micro * 2 * dp,
                           lr=1e-4)
        pcfg = ParallelConfig(num_microbatches=num_micro,
                              data_parallel_size=dp,
                              tensor_parallel_size=tp,
                              use_distributed_optimizer=zero1,
                              quantized_grad_reduce=quant,
                              overlap_grad_reduce=overlap,
                              overlap_param_gather=overlap,
                              grad_rs_bucket_mb=0.05 if overlap else 4.0)
        if zero1:
            ospecs = optimizer_state_specs(cfg, tmpl, dp, True,
                                           base_specs=pspecs,
                                           overlap_grads=overlap)
            osh = jax.tree.map(lambda s: NamedSharding(mesh, s), ospecs,
                               is_leaf=lambda x: isinstance(x, P))
            opt_state = jax.jit(
                lambda p: init_optimizer_state(p, tcfg),
                out_shardings=OptimizerState(
                    step=NamedSharding(mesh, P()), m=osh, v=osh,
                    scaler=None),
            )(params)
        else:
            opt_state = init_optimizer_state(params, tcfg)
        # graft-contract: train.step
        step = jax.jit(
            make_train_step(model, tcfg, pcfg,
                            contract_key=("audit", mesh_tag),
                            contract_owner=None),
            donate_argnums=(0, 1))
        tokens = jnp.asarray(
            np.zeros((num_micro, 2 * dp, cfg.seq_length), np.int32))
        tokens = jax.device_put(
            tokens, NamedSharding(mesh, P(None, "data", None)))
        batch = {"tokens": tokens, "labels": tokens}
        # the PRODUCTION specialization: the trainer always passes a
        # traced fp32 spike threshold (loss-watchdog in-step skip gate,
        # trainer.py "ONE trace either way"), so the audited HLO must
        # contain the found_inf machinery traffic actually runs. rng
        # stays None — the no-dropout config's own specialization.
        lower_args = (params, opt_state, batch, jnp.float32(1e-4),
                      jnp.float32(0.0), None, jnp.float32(np.inf))
        if costs:
            from megatron_llm_tpu.telemetry import CostRegistry

            registry = CostRegistry().attach()
            try:
                rec = registry.capture("train.step", ("audit", mesh_tag),
                                       step, lower_args)
                res = audit_lowered("train.step", mesh_tag, step,
                                    lower_args)
            finally:
                registry.detach()
            res.facts["costs"] = True
            res.facts["cost_records"] = registry.captures
            if rec is None or rec.flops is None:
                res.fail(
                    "+costs row: CostRegistry.capture returned no FLOPs "
                    "for the train step — the mint-time capture path "
                    "the trainer's --device_cost_registry rides is "
                    "broken")
            return res
        if not telemetry:
            return audit_lowered("train.step", mesh_tag, step,
                                 lower_args)
        from megatron_llm_tpu.telemetry import FlightRecorder, SpanTracer

        tracer = SpanTracer(enabled=True)
        recorder = FlightRecorder(64)
        tracer.set_context(step=0)
        with tracer.span("train-step"):
            res = audit_lowered("train.step", mesh_tag, step, lower_args)
        recorder.record("step", step=0, loss=0.0)
        res.facts["telemetry"] = True
        res.facts["telemetry_events"] = len(tracer.events())
        return res
    finally:
        destroy_parallel()


def _audit_generate_tokens() -> TargetResult:
    import jax.numpy as jnp
    import numpy as np

    from megatron_llm_tpu.inference.generation import generate_tokens

    model, params = _tiny_model()
    tokens = jnp.asarray(np.zeros((1, 16), np.int32))
    lengths = jnp.asarray(np.asarray([8], np.int32))
    return audit_lowered(
        "generate.tokens", "single", generate_tokens,
        (model, params, tokens, lengths, 8),
        {"top_k": 1, "vocab_size": 256})


def _audit_chunk_topk() -> TargetResult:
    import jax.numpy as jnp
    import numpy as np

    from megatron_llm_tpu.data.realm_index import _chunk_topk

    fn = _chunk_topk()
    q = jnp.asarray(np.zeros((4, 8), np.float32))
    ev = jnp.asarray(np.zeros((16, 8), np.float32))
    return audit_lowered(
        "realm.chunk_topk", "single", fn,
        (q, ev, jnp.asarray(16, jnp.int32)), {"k": 2})


def _audit_flash_attention() -> TargetResult:
    import jax.numpy as jnp
    import numpy as np

    from megatron_llm_tpu.ops.flash_attention import flash_attention

    # the dense XLA path: the Pallas kernel is TPU-gated and its CPU
    # interpret mode IS a host callback by construction. Layouts: q
    # (b, s, g, qpk, d), k/v (b, t, g, d) — the grouped GQA layout.
    q = jnp.asarray(np.zeros((1, 32, 2, 2, 16), np.float32))
    kv = jnp.asarray(np.zeros((1, 32, 2, 16), np.float32))
    return audit_lowered(
        "ops.flash_attention", "single", flash_attention,
        (q, kv, kv), {"causal": True, "use_pallas": False})


def check_contract_markers(root: str) -> List[str]:
    """Every `# graft-contract: <name>` marker in the package must name
    a REGISTERED contract — a marker that quiets the GR007 lint while
    pointing at nothing would make the registry a fiction. Returns a
    list of problems (empty = consistent). Any package module that
    DECLARES contracts is imported first, so the registered set does not
    depend on which audit targets happened to be constructed (a contract
    like train.pipeline_step registers in a module no CPU target
    lowers)."""
    import importlib

    from megatron_llm_tpu.analysis.lint import _CONTRACT_MARK

    problems = []
    pkg = os.path.join(root, "megatron_llm_tpu")
    marked: List[tuple] = []  # (path, lineno, line)
    declaring: List[str] = []  # dotted module names to import
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "analysis")]
        for f in sorted(filenames):
            if not f.endswith(".py"):
                continue
            p = os.path.join(dirpath, f)
            declares = False
            with open(p, "r", encoding="utf-8") as fh:
                for lineno, line in enumerate(fh, 1):
                    if "compile_contract(" in line \
                            or "register_contract(" in line:
                        declares = True
                    if _CONTRACT_MARK in line:
                        marked.append((p, lineno, line))
            if declares:
                rel = os.path.relpath(p, root)[:-len(".py")]
                mod = rel.replace(os.sep, ".")
                declaring.append(
                    mod[:-len(".__init__")] if mod.endswith(".__init__")
                    else mod)
    for mod in declaring:
        try:
            importlib.import_module(mod)
        except Exception as e:  # noqa: BLE001 — report, don't crash
            problems.append(
                f"{mod}: declares contracts but failed to import for "
                f"marker checking: {e!r}")
    registered = set(all_contracts())
    for p, lineno, line in marked:
        name = line.split(_CONTRACT_MARK, 1)[1].strip()
        name = name.split()[0] if name else ""
        if name not in registered:
            rel = os.path.relpath(p, root)
            problems.append(
                f"{rel}:{lineno}: marker names unregistered "
                f"contract {name!r} (registered: "
                f"{sorted(registered)})")
    return problems


def _check_zero1_state_bytes(results: List[TargetResult]) -> None:
    """ISSUE 10 acceptance: per-device optimizer-state bytes under
    zero1 must be <= replicated_bytes/dp (+ the documented replicated
    residue and slack), read from the AOT memory_analysis argument
    bytes of the SAME train step on the SAME mesh. The m/v trees are
    the only args whose sharding changes between the two rows, so the
    args-bytes delta IS the sharded optimizer state."""
    by_tag = {r.mesh_tag: r for r in results if r.contract == "train.step"}
    # NOTE: the +overlap rows lower a 4-layer variant config, so their
    # args bytes are not comparable to the 2-layer dp2 baseline here;
    # the overlap layout's 1/dp state sharding is pinned by
    # tests/test_overlap.py (optimizer_state_specs unit + live-sharding
    # gauges) instead.
    for base_tag, z_tag in (("dp2", "dp2+zero1"),
                            ("dp2tp2", "dp2tp2+zero1")):
        base, z = by_tag.get(base_tag), by_tag.get(z_tag)
        if base is None or z is None:
            continue
        a_rep = base.facts.get("args_bytes")
        a_z = z.facts.get("args_bytes")
        if not isinstance(a_rep, int) or not isinstance(a_z, int):
            continue  # platform without memory_analysis
        dp, tp = _mesh_shape_for_tag(z_tag)
        # m + v at the audit config: every leaf fp32, same sizes as the
        # (replicated-over-dp) params — 2 trees of them
        import jax
        import numpy as np

        from megatron_llm_tpu.models import LlamaModel

        cfg = _audit_train_config()
        tmpl = jax.eval_shape(LlamaModel(cfg).init, jax.random.key(0))
        opt_bytes = 2 * sum(int(np.prod(l.shape)) * 4
                            for l in jax.tree.leaves(tmpl))
        saved = a_rep - a_z
        # expected saving: the sharded fraction of m/v moves to 1/dp per
        # device — from a baseline that is ALREADY 1/tp per device for
        # the tp-sharded leaves (approximated as the whole tree / tp;
        # norm-scale leaves are O(h) noise at this config). 10% slack
        # absorbs the replicated residue and layout padding.
        expected = opt_bytes / tp * (1 - 1.0 / dp)
        z.facts["opt_state_args_saving_bytes"] = saved
        z.facts["opt_state_expected_saving_bytes"] = int(expected)
        if saved < expected * 0.9:
            z.fail(
                f"per-device optimizer-state bytes not ~1/dp: zero1 args "
                f"{a_z} vs replicated {a_rep} saves {saved} bytes, "
                f"expected >= {int(expected * 0.9)} (m+v {opt_bytes} B "
                f"sharded {dp}-way) — the optimizer_state_specs sharding "
                f"is not reaching the compiled artifact")


def _check_overlap_schedule(results: List[TargetResult]) -> None:
    """ISSUE 12 acceptance: the scheduled train.step specializations
    must show the interleaving STRUCTURALLY in the compiled artifact.

    Per overlap row, against the SAME OverlapPlan the step builds
    (recomputed here from the audit config, so the pin can never drift
    from the runtime's bucket math):

    - the per-bucket granularity is real: reduce-scatter (or, quantized,
      all-to-all) op count == layer groups + aux buckets, and all-gather
      count covers the per-bucket gather units — not one fused sweep;
    - the wire is unchanged: the overlap plan's comm_bytes_per_reduce
      equals the eager plan's (regrouping moves no gradient bytes);
    - the schedule interleaves: >= groups-1 gaps between consecutive
      reduce ops carry >= 2 heavy compute ops (the next group's backward
      layer scans) — the eager row reduces everything after ONE
      monolithic backward, so its reduce ops cannot show this pattern at
      group granularity;
    - async pairs: an honest, MEASURED 0 on this CPU backend (no async
      collectives); on an async backend (TPU) the same rows must show
      -start/-done pairs with compute between them instead.
    """
    import jax

    from megatron_llm_tpu.models import LlamaModel
    from megatron_llm_tpu.optimizer.zero1 import (
        build_overlap_plan,
        build_zero1_plan,
    )

    by_tag = {r.mesh_tag: r for r in results if r.contract == "train.step"}
    cfg = _audit_train_config(num_layers=4)  # the overlap rows' config
    tmpl = jax.eval_shape(LlamaModel(cfg).init, jax.random.key(0))

    for z_tag, wire_op in (("dp2+zero1+overlap", "reduce-scatter"),
                           ("dp2+zero1-quant+overlap", "all-to-all")):
        row = by_tag.get(z_tag)
        if row is None:
            continue
        dp, _tp = _mesh_shape_for_tag(z_tag)
        plan = build_overlap_plan(cfg, tmpl, dp, bucket_mb=0.05)
        eager_plan = build_zero1_plan(cfg, tmpl, dp, bucket_mb=4.0)
        quant = "-quant" in z_tag
        n_groups = len(plan.groups)
        n_buckets = n_groups + len([b for b in plan.aux.buckets if b])
        rep = row.facts.get("overlap") or {}
        counts = rep.get("collective_counts", {})
        row.facts["overlap_plan"] = {
            "groups": n_groups, "buckets": n_buckets,
            "comm_bytes": plan.comm_bytes_per_reduce(quant),
            "eager_comm_bytes": eager_plan.comm_bytes_per_reduce(quant),
        }
        # the fp gradient PAYLOAD must be exactly the eager plan's —
        # regrouping moves no data bytes. The quantized totals may
        # differ only in per-bucket chunk-scale PADDING (each bucket
        # pads to dp x QUANT_CHUNK elements independently): bound it.
        if plan.comm_bytes_per_reduce(False) != \
                eager_plan.comm_bytes_per_reduce(False):
            row.fail(
                f"overlap regrouping changed the fp gradient wire "
                f"bytes: {plan.comm_bytes_per_reduce(False)} vs eager "
                f"{eager_plan.comm_bytes_per_reduce(False)} — the "
                f"sharded/residue split drifted between the plans")
        if quant:
            n_eager = len([b for b in eager_plan.buckets if b])
            pad_bound = (n_buckets + n_eager) * dp * 4
            delta = abs(plan.comm_bytes_per_reduce(True)
                        - eager_plan.comm_bytes_per_reduce(True))
            if delta > pad_bound:
                row.fail(
                    f"quantized wire bytes differ by {delta} (> the "
                    f"{pad_bound}-byte chunk-padding bound): the int8 "
                    f"payload itself changed, not just scale padding")
        if rep.get("async_pairs"):
            # async backend: the real evidence — pairs with compute
            # between start and done
            if (rep.get("min_ops_between_pairs") or 0) < 1:
                row.fail(
                    f"async collective pairs present but at least one "
                    f"pair has NO compute between -start and -done "
                    f"({rep}) — the scheduler serialized the wire")
            continue
        # sync (CPU) backend: structural interleave of the scheduled
        # module. Quantized buckets exchange data+scales = 2 all-to-all
        # per issue point; fp buckets are 1 reduce-scatter each.
        per_bucket = 2 if quant else 1
        want = n_buckets * per_bucket
        got = counts.get(wire_op, 0)
        if got != want:
            row.fail(
                f"{wire_op} count {got} != {want} (= {n_buckets} "
                f"buckets x {per_bucket}): the per-bucket issue points "
                f"did not survive to the compiled schedule")
        gaps = rep.get("compute_between", {}).get(wire_op, [])
        deep = sum(1 for g in gaps if g >= 2)
        row.facts["overlap_interleaved_gaps"] = deep
        if deep < n_groups - 1:
            row.fail(
                f"only {deep} of the {wire_op} gaps carry >= 2 heavy "
                f"compute ops (need >= {n_groups - 1} = group "
                f"boundaries; gaps: {gaps}) — the backward-interleaved "
                f"issue points collapsed into a post-backward clump")


def _check_telemetry_parity(results: List[TargetResult]) -> None:
    """ISSUE 13 + 15 acceptance: specializations lowered with telemetry
    live (span tracer + flight recorder around the mint) OR with the
    cost registry capturing (the ISSUE 15 mint-time hook) must be the
    SAME compiled program family as the plain rows — identical
    collective inventory, zero host callbacks, same fp64 verdict, and
    (cost rows) identical compiled FLOPs: capture reads the artifact,
    it may never change it. All emission is host bookkeeping outside
    jit by design; this pin turns that design rule into a gate, so
    threading a span, an event, or a cost probe into a jitted step
    fails the audit instead of a production run."""
    # engine rows: telemetry-on / cost-on vs the plain fp engine
    base: Dict[str, TargetResult] = {}
    for r in results:
        if (r.contract.startswith("engine.")
                and "telemetry" not in r.facts
                and "costs" not in r.facts
                and "quantized" not in r.facts):
            base.setdefault(r.contract, r)
    pairs = [(r, base.get(r.contract)) for r in results
             if r.contract.startswith("engine.")
             and (r.facts.get("telemetry") or r.facts.get("costs"))]
    # train.step: the +telemetry / +costs tags vs their base tag
    by_tag = {r.mesh_tag: r for r in results
              if r.contract == "train.step"}
    for tag, r in by_tag.items():
        for suffix in ("+telemetry", "+costs"):
            if tag.endswith(suffix):
                pairs.append((r, by_tag.get(tag[:-len(suffix)])))
    for r, b in pairs:
        what = "cost-registry-on" if r.facts.get("costs") \
            else "telemetry-on"
        if b is None:
            r.fail(f"no plain twin row to compare the {what} "
                   f"specialization against — the parity pin needs "
                   f"both lowered")
            continue
        if r.facts.get("collectives") != b.facts.get("collectives"):
            r.fail(
                f"{what} collective inventory "
                f"{r.facts.get('collectives')} != plain "
                f"{b.facts.get('collectives')} ({b.mesh_tag}): "
                f"instrumentation leaked into the jitted program — "
                f"emission must stay host-side (telemetry/ contract)")
        if r.facts.get("host_callbacks"):
            r.fail(
                f"{what} specialization lowered host callbacks "
                f"{r.facts['host_callbacks']}: an emitter/probe is "
                f"being called FROM traced code")
        if r.facts.get("f64") != b.facts.get("f64"):
            r.fail(f"{what} fp64 verdict differs from the plain row")
        if (r.facts.get("costs") and "flops" in r.facts
                and "flops" in b.facts
                and r.facts["flops"] != b.facts["flops"]):
            r.fail(
                f"cost-registry-on compiled FLOPs {r.facts['flops']} "
                f"!= plain {b.facts['flops']}: the capture perturbed "
                f"the artifact it claims to measure")


def audit_repo(root: str) -> dict:
    """Run the full audit: lower every reference target, check marker
    consistency, and return a JSON-able report. Requires >= 4 devices
    for the dp2tp2 mesh (tests/tools provision virtual CPU devices)."""
    import jax

    results: List[TargetResult] = []
    results.extend(_audit_engine())
    n_dev = len(jax.devices())
    # the ZeRO-1 rows (ISSUE 10): BOTH the replicated and the zero1
    # specializations lower on the dp meshes, pinning the explicit
    # decomposition's collective inventory (reduce-scatter on the
    # pure-dp mesh; the quantized variant's all-to-all) and the
    # dp-sharded optimizer-state args bytes below.
    for tag in ("tp2", "dp2", "dp2+telemetry", "dp2+costs",
                "dp2+zero1",
                "dp2+zero1-quant",
                "dp2+zero1+overlap", "dp2+zero1-quant+overlap",
                "dp2tp2", "dp2tp2+zero1"):
        dp, tp = _mesh_shape_for_tag(tag)
        if dp * tp > n_dev:
            r = TargetResult(contract="train.step", mesh_tag=tag)
            r.fail(f"needs {dp * tp} devices, have {n_dev} — provision "
                   f"virtual CPU devices (utils/virtual_mesh.py)")
            results.append(r)
            continue
        results.append(_audit_train_step(tag))
    _check_zero1_state_bytes(results)
    _check_overlap_schedule(results)
    _check_telemetry_parity(results)
    results.append(_audit_generate_tokens())
    results.append(_audit_chunk_topk())
    results.append(_audit_flash_attention())

    marker_problems = check_contract_markers(root)
    audited = {r.contract for r in results}
    report = {
        "ok": all(r.ok for r in results) and not marker_problems,
        "targets": [r.to_dict() for r in results],
        "entry_points_audited": sorted(audited),
        "mesh_tags": sorted({r.mesh_tag for r in results}),
        "marker_problems": marker_problems,
        "contracts_registered": sorted(all_contracts()),
        "known_failures": KNOWN_FAILURES_DOC,
        "note": (
            "temp-bytes budgets and collective inventories are pinned at "
            "the tiny audit reference configs; pre-existing slow-suite "
            f"failures are triaged in {KNOWN_FAILURES_DOC}"),
    }
    return report
