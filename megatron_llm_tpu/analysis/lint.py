"""graft-check pass 1: an AST linter for JAX trace discipline.

Pure-`ast`, no jax import — the rules encode how THIS repo is allowed
to touch the tracer:

- GR001 tracer-host-sync      .item() / float() / int() / bool() /
                              np.asarray / np.array on non-constant
                              values inside traced code — each forces a
                              concretization that either fails under
                              jit or silently pins a host round-trip.
- GR002 jit-in-loop           jax.jit / pjit constructed inside a
                              for/while body or a comprehension: a
                              fresh wrapper per iteration defeats jit's
                              call cache and retraces every time.
- GR003 unhashable-static     static_argnums / static_argnames given a
                              list/set/dict display: unhashable the
                              moment the wrapper is reused as a cache
                              key (functools.partial application, LRU
                              keys) — tuples or bare ints only.
- GR004 host-entropy          time.* / random.* / np.random.* inside
                              traced code: evaluated ONCE at trace
                              time, then frozen into the executable —
                              the classic "my timestamp never changes"
                              / "my noise is identical every step" bug.
- GR005 unordered-pytree      iterating a set (display or set(...)
                              call) to build containers inside traced
                              code: set order is hash-seed dependent,
                              so the pytree structure — and the
                              executable — can differ between
                              processes that must agree (multi-host
                              lockstep dispatch).
- GR006 hot-loop-host-sync    device_get / block_until_ready /
                              np.asarray / float() / int() inside the
                              engine serve loop's per-round path and
                              the trainer's step path (HOT_PATHS):
                              every one is a device stall per round;
                              deliberate ones carry a baseline
                              justification.
- GR007 unregistered-jit      bare jax.jit in megatron_llm_tpu/ with no
                              compile-contract registration marker: an
                              entry point the AOT audit cannot see.
                              Mark registered sites with a
                              `# graft-contract: <name>` comment.

Accepted findings live in `lint_baseline.json` next to this file, one
justification per finding key. Keys are line-number-free
(`rule:path:qualname:detail#ordinal`) so refactors that only move code
do not churn the baseline.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "Finding",
    "RULES",
    "HOT_PATHS",
    "lint_source",
    "lint_paths",
    "default_paths",
    "load_baseline",
    "apply_baseline",
]

RULES: Dict[str, str] = {
    "GR001": "tracer-host-sync",
    "GR002": "jit-in-loop",
    "GR003": "unhashable-static",
    "GR004": "host-entropy-in-trace",
    "GR005": "unordered-pytree-iteration",
    "GR006": "hot-loop-host-sync",
    "GR007": "unregistered-jit-entry",
}

# GR006 scope: the functions whose per-call latency IS the product
# (one scheduler round / one optimizer step). Qualnames per repo-relative
# path; extend when a new hot loop is built.
HOT_PATHS: Dict[str, Set[str]] = {
    "megatron_llm_tpu/inference/engine.py": {
        "DecodeEngine.step",
        "DecodeEngine._step_inner",
        "DecodeEngine._decode_round",
        "DecodeEngine._mixed_round",
        "DecodeEngine._spec_round",
        "DecodeEngine._book_token",
        "DecodeEngine._admit",
        # ISSUE 15 device-cost accounting: per-retire cost record,
        # per-round modeled-vs-measured note, per-round sentinel feed —
        # pricing a round must never cost a transfer (the mint-time
        # registry record exists so it doesn't). Fixtures
        # gr006_cost_{good,bad}.py pin the pattern.
        "DecodeEngine._retire",
        "DecodeEngine._request_cost",
        "DecodeEngine._note_dispatch",
        "DecodeEngine._sentinel_observe",
    },
    "megatron_llm_tpu/training/trainer.py": {
        "Trainer.train_step",
        "Trainer.train",
    },
    # telemetry emit sites (ISSUE 13): called once or more per engine
    # round / train step — per-round span/event/histogram bookkeeping
    # must stay pure host arithmetic, never a device sync. The fixtures
    # gr006_span_{good,bad}.py pin the pattern.
    "megatron_llm_tpu/telemetry/trace.py": {
        "SpanTracer.span",
        "SpanTracer.instant",
        "SpanTracer.complete",
        "SpanTracer.set_context",
        "SpanTracer._push",
        "SpanTracer._ts",
        "SpanTracer._tid",
        "_Span.__enter__",
        "_Span.__exit__",
    },
    "megatron_llm_tpu/telemetry/recorder.py": {
        "FlightRecorder.record",
        "FlightRecorder.note_counters",
    },
    "megatron_llm_tpu/telemetry/prometheus.py": {
        "Histogram.observe",
    },
    # ISSUE 15 goodput/cost/sentinel emit sites: per-step ledger adds,
    # per-round registry lookups + roofline math, per-step/round
    # sentinel verdicts — all pure host arithmetic by contract (the
    # mint-time capture is the ONLY place the registry touches jax,
    # and it is not on these paths)
    "megatron_llm_tpu/telemetry/goodput.py": {
        "GoodputLedger.note",
        "GoodputLedger.wall_s",
    },
    "megatron_llm_tpu/telemetry/costs.py": {
        "CostRegistry.record",
        "CostRecord.modeled_seconds",
    },
    "megatron_llm_tpu/telemetry/sentinel.py": {
        "PerfSentinel.observe",
        "RobustWindow.push",
        "RobustWindow.threshold",
    },
}

# Transform entry points whose function arguments run under trace.
_TRACE_WRAPPERS = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad", "scan",
    "while_loop", "fori_loop", "cond", "switch", "checkpoint", "remat",
    "shard_map",
}

_CONTRACT_MARK = "graft-contract:"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    col: int
    qualname: str
    detail: str
    message: str
    ordinal: int = 0

    @property
    def key(self) -> str:
        return (f"{self.rule}:{self.path}:{self.qualname}:"
                f"{self.detail}#{self.ordinal}")

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "name": RULES[self.rule], "path": self.path,
            "line": self.line, "col": self.col, "qualname": self.qualname,
            "detail": self.detail, "message": self.message, "key": self.key,
        }


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan' for Attribute(Name) chains; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_callable(node: ast.AST) -> bool:
    """The node names jax.jit/pjit itself (not a transform like vmap)."""
    chain = _attr_chain(node)
    return chain in {"jit", "pjit", "jax.jit", "jax.pjit"}


def _is_trace_wrapper_call(call: ast.Call) -> bool:
    chain = _attr_chain(call.func)
    if chain is None:
        return False
    leaf = chain.rsplit(".", 1)[-1]
    if leaf not in _TRACE_WRAPPERS:
        return False
    # tree.map-style utilities share no leaf with _TRACE_WRAPPERS, so a
    # leaf match (qualified or bare) is enough for this repo's idiom.
    return True


def _partial_of_jit(call: ast.Call) -> bool:
    """functools.partial(jax.jit, ...) — the decorator idiom."""
    chain = _attr_chain(call.func)
    if chain not in {"partial", "functools.partial"}:
        return False
    return bool(call.args) and _is_jit_callable(call.args[0])


class _ModuleIndex:
    """First pass: which FunctionDef / Lambda NODES are traced.

    A `jax.jit(step)`-style reference marks the def it actually
    resolves to: the def whose enclosing scope (function, lambda, class
    or module) is an ancestor of the referencing call. Scope-aware on
    purpose — `DecodeEngine.step` (a host-side scheduler method) must
    not become "traced" because some builder jits a LOCAL `step`."""

    _SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
               ast.ClassDef, ast.Module)

    def __init__(self, tree: ast.Module):
        self.traced_ids: Set[int] = set()
        parent: Dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parent[id(child)] = node

        def scope_of(node: ast.AST) -> ast.AST:
            n = parent.get(id(node))
            while n is not None and not isinstance(n, self._SCOPES):
                n = parent.get(id(n))
            return n if n is not None else tree

        def scope_chain(node: ast.AST) -> List[ast.AST]:
            chain, n = [], scope_of(node)
            while n is not None:
                chain.append(n)
                n = scope_of(n) if not isinstance(n, ast.Module) else None
            return chain

        defs: Dict[str, List[Tuple[ast.AST, ast.AST]]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(
                    (node, scope_of(node)))

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if not (_is_trace_wrapper_call(node) or _partial_of_jit(node)):
                continue
            args = node.args[1:] if _partial_of_jit(node) else node.args
            chain = None
            for a in args:
                if isinstance(a, ast.Lambda):
                    self.traced_ids.add(id(a))
                elif isinstance(a, ast.Name):
                    if chain is None:
                        chain = scope_chain(node)
                    chain_ids = {id(s) for s in chain}
                    for d, d_scope in defs.get(a.id, []):
                        if id(d_scope) in chain_ids:
                            self.traced_ids.add(id(d))


def _decorator_traced(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if _is_jit_callable(target):
            return True
        if isinstance(dec, ast.Call) and (_partial_of_jit(dec)
                                          or _is_trace_wrapper_call(dec)):
            return True
        chain = _attr_chain(target)
        if chain and chain.rsplit(".", 1)[-1] in _TRACE_WRAPPERS:
            return True
    return False


def _contract_decorated(fn: ast.AST) -> bool:
    """`@compile_contract(...)`-decorated builders register their jit
    site with the registry — GR007's whole point is satisfied."""
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        chain = _attr_chain(target)
        if chain and chain.rsplit(".", 1)[-1] == "compile_contract":
            return True
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str, *, package_scope: bool):
        self.path = path
        self.lines = source.splitlines()
        self.package_scope = package_scope  # GR007 applies
        self.findings: List[Finding] = []
        self._counts: Dict[Tuple[str, str, str], int] = {}
        self._scope: List[str] = []  # qualname parts
        self._traced_depth = 0
        self._loop_depth = 0
        self._hot = HOT_PATHS.get(path, set())
        self._hot_depth = 0
        self._contract_depth = 0
        self._decorator_calls: Set[int] = set()
        self._index: Optional[_ModuleIndex] = None

    # -- emit --------------------------------------------------------------

    def _qual(self) -> str:
        return ".".join(self._scope) or "<module>"

    def _emit(self, rule: str, node: ast.AST, detail: str, message: str):
        ckey = (rule, self._qual(), detail)
        n = self._counts.get(ckey, 0)
        self._counts[ckey] = n + 1
        self.findings.append(Finding(
            rule=rule, path=self.path, line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0), qualname=self._qual(),
            detail=detail, message=message, ordinal=n))

    def _marked(self, node: ast.AST) -> bool:
        """A `# graft-contract: <name>` comment on the node's line or one
        of the three lines above registers the jit site for GR007."""
        line = getattr(node, "lineno", 0)
        lo = max(0, line - 4)
        return any(_CONTRACT_MARK in ln
                   for ln in self.lines[lo:line])

    # -- scope tracking ----------------------------------------------------

    def run(self, tree: ast.Module):
        self._index = _ModuleIndex(tree)
        self.visit(tree)
        return self.findings

    def _visit_scope(self, node, name: str, traced: bool, hot: bool,
                     contract: bool = False):
        self._scope.append(name)
        self._traced_depth += 1 if traced else 0
        self._hot_depth += 1 if hot else 0
        self._contract_depth += 1 if contract else 0
        self.generic_visit(node)
        self._contract_depth -= 1 if contract else 0
        self._hot_depth -= 1 if hot else 0
        self._traced_depth -= 1 if traced else 0
        self._scope.pop()

    def visit_FunctionDef(self, node):
        traced = (_decorator_traced(node)
                  or id(node) in self._index.traced_ids)
        qual = ".".join(self._scope + [node.name])
        # GR007 on jit DECORATORS: `@jax.jit` / `@partial(jax.jit, ...)`
        # on a package function is an entry point too
        if self.package_scope and not _contract_decorated(node) \
                and not self._contract_depth:
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                is_jit = _is_jit_callable(target) or (
                    isinstance(dec, ast.Call) and _partial_of_jit(dec))
                if isinstance(dec, ast.Call) and is_jit:
                    # one finding per decorator site, not a second one
                    # when visit_Call reaches the same node
                    self._decorator_calls.add(id(dec))
                if is_jit and not self._marked(dec) \
                        and not self._marked(node):
                    self._scope.append(node.name)
                    self._emit(
                        "GR007", dec, "bare-jit-decorator",
                        "jitted entry point outside the compile-contract "
                        "registry: register a contract and mark the site "
                        "with `# graft-contract: <name>`, or baseline "
                        "with justification")
                    self._scope.pop()
        self._visit_scope(node, node.name, traced, qual in self._hot,
                          contract=_contract_decorated(node))

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._visit_scope(node, node.name, False, False)

    def visit_Lambda(self, node):
        traced = id(node) in self._index.traced_ids
        self._visit_scope(node, "<lambda>", traced, False)

    def visit_For(self, node):
        self._check_iter_order(node.iter)
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    def visit_While(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    def visit_comprehension_like(self, node):
        for gen in node.generators:
            self._check_iter_order(gen.iter)
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_ListComp = visit_comprehension_like
    visit_SetComp = visit_comprehension_like
    visit_DictComp = visit_comprehension_like
    visit_GeneratorExp = visit_comprehension_like

    # -- rules -------------------------------------------------------------

    def _check_iter_order(self, it: ast.AST):
        """GR005: iterating a set to build structure inside traced code."""
        if not self._traced_depth:
            return
        is_set = isinstance(it, ast.Set) or (
            isinstance(it, ast.Call) and _attr_chain(it.func) == "set")
        if is_set:
            self._emit(
                "GR005", it, "set-iteration",
                "iteration order of a set is hash-seed dependent inside "
                "traced code: the pytree/executable structure it builds "
                "can differ across processes that must dispatch in "
                "lockstep — sort it or use a tuple/dict")

    def visit_Call(self, node: ast.Call):
        chain = _attr_chain(node.func)
        in_traced = self._traced_depth > 0
        in_hot = self._hot_depth > 0

        # GR002: jit constructed inside a loop/comprehension
        if (_is_jit_callable(node.func) or _partial_of_jit(node)) \
                and self._loop_depth:
            self._emit(
                "GR002", node, "jit-in-loop",
                "jax.jit constructed inside a loop: every iteration "
                "mints a fresh wrapper with an empty call cache, so "
                "every call retraces — hoist the jit (or cache it, "
                "LRU-bounded like api._pp_decode_fn)")

        # GR003: list/set/dict-typed static_argnums|static_argnames
        if _is_jit_callable(node.func) or _partial_of_jit(node):
            for kw in node.keywords:
                if kw.arg in ("static_argnums", "static_argnames") and \
                        isinstance(kw.value,
                                   (ast.List, ast.Set, ast.Dict,
                                    ast.ListComp, ast.SetComp)):
                    self._emit(
                        "GR003", kw.value, kw.arg,
                        f"{kw.arg} given a list/set/dict display: "
                        "unhashable the moment the wrapper is reused as "
                        "a cache key — use a tuple or bare int")

        # GR007: bare jit in package code with no contract marker
        if self.package_scope \
                and (_is_jit_callable(node.func) or _partial_of_jit(node)) \
                and not self._contract_depth \
                and id(node) not in self._decorator_calls \
                and not self._marked(node):
            self._emit(
                "GR007", node, "bare-jit",
                "jax.jit entry point outside the compile-contract "
                "registry: the AOT audit cannot see it. Register a "
                "contract (analysis/contracts.py) and mark the site "
                "with `# graft-contract: <name>`, or baseline with "
                "justification")

        if in_traced:
            # GR001: concretizing calls on traced values
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                self._emit(
                    "GR001", node, ".item()",
                    ".item() inside traced code concretizes the tracer: "
                    "TracerArrayConversionError under jit, silent host "
                    "sync outside — keep it as a device scalar")
            if isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int", "bool") \
                    and node.args \
                    and not isinstance(node.args[0], ast.Constant):
                self._emit(
                    "GR001", node, f"{node.func.id}()",
                    f"{node.func.id}() on a non-constant inside traced "
                    "code concretizes the tracer — use jnp casts "
                    "(astype) to change dtype, or move the conversion "
                    "outside the jitted function")
            if chain in ("np.asarray", "np.array", "numpy.asarray",
                         "numpy.array"):
                self._emit(
                    "GR001", node, chain,
                    "numpy materialization inside traced code forces a "
                    "concrete value (trace-time constant at best, "
                    "TracerArrayConversionError at worst) — use jnp")

            # GR004: host entropy frozen at trace time
            if chain and (chain.startswith("time.")
                          or chain.startswith("random.")
                          or chain.startswith("np.random.")
                          or chain.startswith("numpy.random.")):
                self._emit(
                    "GR004", node, chain,
                    f"{chain} inside traced code runs ONCE at trace "
                    "time and is frozen into the executable — pass "
                    "times/randomness in as arguments (jax.random for "
                    "on-device RNG)")

        if in_hot:
            # GR006: host syncs in the per-round/per-step hot path
            if chain in ("jax.device_get", "np.asarray", "np.array",
                         "numpy.asarray", "numpy.array"):
                self._emit(
                    "GR006", node, chain or "device_get",
                    f"{chain} in a hot loop is a device->host transfer "
                    "per round — batch it, gate it on need, or move it "
                    "off the round path")
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                self._emit(
                    "GR006", node, "block_until_ready",
                    "block_until_ready in a hot loop serializes host "
                    "and device — the dispatch pipeline exists to "
                    "overlap them")
            if isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int") \
                    and node.args \
                    and not isinstance(node.args[0], ast.Constant):
                self._emit(
                    "GR006", node, f"{node.func.id}()",
                    f"{node.func.id}() in a hot loop blocks on the "
                    "device value if its arg is a jax array — fetch "
                    "once per round as numpy, then index on host")
        self.generic_visit(node)


def lint_source(source: str, path: str, *, package_scope: bool = False
                ) -> List[Finding]:
    tree = ast.parse(source, filename=path)
    return _Linter(path, source, package_scope=package_scope).run(tree)


def default_paths(root: str) -> List[str]:
    """The lint surface: the package, the task/tool scripts, and the
    top-level entry scripts. Tests and fixtures are excluded — they
    deliberately exercise anti-patterns — and so is the analysis
    package itself: the auditor's one-shot reference jits ARE its
    measurement apparatus, not serving/training entry points."""
    out: List[str] = []
    for sub in ("megatron_llm_tpu", "tasks", "tools"):
        for dirpath, dirnames, filenames in os.walk(os.path.join(root, sub)):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "analysis")]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    for f in ("bench.py", "verify_correctness.py", "finetune.py",
              "pretrain_bert.py", "pretrain_t5.py", "pretrain_ict.py"):
        p = os.path.join(root, f)
        if os.path.exists(p):
            out.append(p)
    return out


def lint_paths(paths: List[str], root: str) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        with open(p, "r", encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(lint_source(
            src, rel, package_scope=rel.startswith("megatron_llm_tpu/")))
    return findings


# -- baseline ---------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, str]:
    """key -> justification. Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    entries = data["entries"] if isinstance(data, dict) else data
    out = {}
    for e in entries:
        if not e.get("justification", "").strip():
            raise ValueError(
                f"baseline entry {e.get('key')!r} has no justification — "
                "every accepted finding must say WHY it is accepted")
        out[e["key"]] = e["justification"]
    return out


def apply_baseline(findings: List[Finding], baseline: Dict[str, str]
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """-> (new, accepted, stale-baseline-keys)."""
    seen = set()
    new, accepted = [], []
    for f in findings:
        if f.key in baseline:
            accepted.append(f)
            seen.add(f.key)
        else:
            new.append(f)
    stale = sorted(set(baseline) - seen)
    return new, accepted, stale
