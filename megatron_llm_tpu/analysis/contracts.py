"""Compile-contract registry: declared budgets for every jitted entry point.

The repo's correctness story leaned on scattered per-test executable
counters (realm_index single-executable, engine horizon buckets,
spec-decode width-k) and per-PR "one trace per pow2 bucket" claims that
nothing enforced globally. This module is the ONE counting mechanism:

- `@compile_contract(name, max_variants=..., collectives=...,
  tmp_bytes_budget=...)` decorates a jitted-entry-point BUILDER
  (e.g. engine._make_step_fn). Each builder invocation records a
  VARIANT — one (builder, static-key) executable — under the owner that
  minted it (an engine instance, a trainer, or the module-global cache).
- Recording past the declared budget raises `ContractViolation` AT MINT
  TIME: a retrace storm fails loudly where it starts, not as a latency
  mystery three layers up. Call sites that know a tighter config-derived
  budget (the engine's pow2 bucket math) pass `contract_budget=`.
- Caches that EVICT executables (the LRU prefill/pp-decode caches)
  call `release_variant` so the live count tracks cache occupancy.
- `analysis/audit.py` AOT-lowers each registered entry point on a CPU
  mesh and checks the rest of the declaration (collective inventory per
  mesh shape, no host callbacks, no fp64, temp-memory budget) against
  the compiled artifact.

Import-light by design: no jax at module scope — every DecodeEngine
constructor and test imports this.
"""

from __future__ import annotations

import functools
import threading
import weakref
from dataclasses import dataclass
from typing import Any, Callable, Dict, FrozenSet, Mapping, Optional

__all__ = [
    "CompileContract",
    "ContractViolation",
    "compile_contract",
    "get_contract",
    "all_contracts",
    "record_variant",
    "release_variant",
    "register_contract",
    "variants",
    "variant_count",
    "total_live_variants",
    "get_builder",
    "jit_cache_size",
    "add_mint_listener",
    "remove_mint_listener",
]


class ContractViolation(AssertionError):
    """A jitted entry point broke its declared compile contract (variant
    budget exceeded at mint time, or an audit check failed). Deliberately
    an AssertionError: test suites that pin executable counts fail the
    same way they always did, through the one shared counter."""


# Collective-inventory keys are the optimized-HLO opcode family names
# the auditor greps for (analysis/audit.py); a contract declares, per
# mesh-shape tag, EXACTLY the set allowed to appear.
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
    "collective-broadcast",
)


@dataclass(frozen=True)
class CompileContract:
    """The declaration one jitted entry point audits against.

    - `max_variants`: absolute ceiling on live executables this entry
      point may hold per owner (None = uncounted). Call sites may pass a
      TIGHTER config-derived budget at record time; this value is the
      registry-wide backstop and what the audit's bucket math checks.
    - `collectives`: mesh-shape tag -> frozenset of collective opcodes
      allowed in the optimized HLO ("single" tags the no-mesh case,
      where the set is empty). None = not audited for collectives.
    - `tmp_bytes_budget`: compiled temp_size_in_bytes ceiling for the
      audit reference config (tiny model on the CPU mesh — the budget
      pins RELATIVE regressions: a remat/layout change that blows it up
      is visible long before a production shape exists).
    - `allow_host_callbacks` / `allow_f64`: both audited to "absent"
      unless explicitly allowed.
    """

    name: str
    max_variants: Optional[int] = None
    collectives: Optional[Mapping[str, FrozenSet[str]]] = None
    tmp_bytes_budget: Optional[int] = None
    allow_host_callbacks: bool = False
    allow_f64: bool = False
    notes: str = ""

    def __post_init__(self):
        if self.collectives is not None:
            for tag, ops in self.collectives.items():
                bad = set(ops) - set(COLLECTIVE_OPS)
                if bad:
                    raise ValueError(
                        f"contract {self.name!r}: unknown collective "
                        f"opcodes {sorted(bad)} for mesh {tag!r} "
                        f"(known: {COLLECTIVE_OPS})")


_LOCK = threading.RLock()
_REGISTRY: Dict[str, CompileContract] = {}
_BUILDERS: Dict[str, Callable] = {}

# Variant store: name -> owner-token -> {key: None} (an insertion-ordered
# set; dict for O(1) discard). Owner tokens are id(owner) with a weakref
# finalizer so a garbage-collected engine's bucket — and its recycled
# id() — can never pollute a later owner's count. None owner = the
# module-global bucket (module-scope executable caches).
_GLOBAL = "<global>"
_VARIANTS: Dict[str, Dict[Any, Dict[Any, None]]] = {}

# Mint listeners (ISSUE 15): callbacks fired once per NEW variant
# record_variant accepts — the hook telemetry/costs.CostRegistry rides
# so the compiled-cost inventory mirrors the executable inventory
# exactly (mint-time only; nothing fires on cache hits or releases).
# Fired OUTSIDE the registry lock: a listener may take its own locks.
_MINT_LISTENERS: list = []


def add_mint_listener(cb) -> None:
    """Register cb(name, key, owner), called once per newly recorded
    variant. Listeners must be cheap host bookkeeping (they run at the
    mint site, which may sit inside a serving round's lazy trace) and
    must never raise — exceptions propagate to the minting caller."""
    with _LOCK:
        if cb not in _MINT_LISTENERS:
            _MINT_LISTENERS.append(cb)


def remove_mint_listener(cb) -> None:
    with _LOCK:
        try:
            _MINT_LISTENERS.remove(cb)
        except ValueError:
            pass


def register_contract(contract: CompileContract,
                      builder: Optional[Callable] = None) -> CompileContract:
    """Install (or replace — module reloads in tests) a contract."""
    with _LOCK:
        _REGISTRY[contract.name] = contract
        if builder is not None:
            _BUILDERS[contract.name] = builder
        _VARIANTS.setdefault(contract.name, {})
    return contract


def get_contract(name: str) -> CompileContract:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"no compile contract registered under {name!r} "
            f"(registered: {sorted(_REGISTRY)})") from None


def all_contracts() -> Dict[str, CompileContract]:
    with _LOCK:
        return dict(_REGISTRY)


def get_builder(name: str) -> Callable:
    """The undecorated builder a contract was registered from (the
    audit constructs entry points through this)."""
    get_contract(name)
    try:
        return _BUILDERS[name]
    except KeyError:
        raise KeyError(
            f"contract {name!r} has no builder (registered via "
            f"register_contract without one — audit it through an "
            f"explicit target spec instead)") from None


def _owner_token(owner: Any):
    return _GLOBAL if owner is None else id(owner)


def _drop_owner(name: str, token) -> None:
    with _LOCK:
        _VARIANTS.get(name, {}).pop(token, None)


def record_variant(name: str, key: Any, owner: Any = None,
                   budget: Optional[int] = None) -> bool:
    """Count one minted executable for `name` under `owner`. Returns
    True when the key is new. Raises ContractViolation when the live
    count would exceed min(budget, contract.max_variants)."""
    contract = get_contract(name)
    limits = [b for b in (budget, contract.max_variants) if b is not None]
    limit = min(limits) if limits else None
    listeners = ()
    with _LOCK:
        token = _owner_token(owner)
        per_name = _VARIANTS.setdefault(name, {})
        bucket = per_name.get(token)
        if bucket is None:
            bucket = per_name[token] = {}
            if owner is not None:
                try:
                    # drop the bucket when the owner dies: id() values
                    # are recycled, and a stale bucket under a recycled
                    # id would hand a brand-new engine another engine's
                    # variant count. ONE finalizer per bucket — not per
                    # registry call — or a long-lived engine's LRU churn
                    # would pile up duplicate finalizers for its lifetime
                    weakref.finalize(owner, _drop_owner, name, token)
                except TypeError:
                    pass  # un-weakrefable owners keep their bucket
        if key in bucket:
            return False
        if limit is not None and len(bucket) + 1 > limit:
            raise ContractViolation(
                f"compile contract {name!r}: minting variant {key!r} "
                f"would exceed the declared budget of {limit} "
                f"executables (live: {sorted(map(repr, bucket))}). "
                f"Either the bucketing that bounds this entry point "
                f"regressed (a retrace storm), or the budget declaration "
                f"must be updated WITH justification "
                f"(docs/GUIDE.md, 'Static analysis & compile contracts')")
        bucket[key] = None
        listeners = tuple(_MINT_LISTENERS)
    for cb in listeners:  # outside the lock, new mints only
        cb(name, key, owner)
    return True


def release_variant(name: str, key: Any, owner: Any = None) -> bool:
    """Un-count an EVICTED executable (LRU caches): the budget bounds
    live executables, which is what the eviction exists to do."""
    get_contract(name)
    with _LOCK:
        bucket = _VARIANTS.get(name, {}).get(_owner_token(owner))
        if bucket is None or key not in bucket:
            return False
        del bucket[key]
        return True


def variants(name: str, owner: Any = None) -> FrozenSet:
    """The live variant-key set for (entry point, owner) — the ONE
    counting mechanism the per-suite executable guards read."""
    get_contract(name)
    with _LOCK:
        bucket = _VARIANTS.get(name, {}).get(_owner_token(owner), {})
        return frozenset(bucket)


def variant_count(name: str, owner: Any = None) -> int:
    return len(variants(name, owner))


def total_live_variants(name: str) -> int:
    """Live executables for `name` summed across ALL owner buckets —
    what the audit report publishes (per-owner counts would read 0 for
    engine-scoped contracts when the reader holds no engine)."""
    get_contract(name)
    with _LOCK:
        return sum(len(b) for b in _VARIANTS.get(name, {}).values())


def jit_cache_size(fn) -> int:
    """Live executables in a jitted fn's own call cache. Builder-minted
    entry points count variants through record_variant; MODULE-LEVEL
    jits (generate_tokens, realm.chunk_topk) are traced per static/shape
    key by jax itself, so their executable count lives in the jit call
    cache — this accessor is the ONE place that touches jax's private
    `_cache_size`, and what the per-suite single-executable guards call
    (tests keep their old assertions as thin wrappers over it)."""
    return int(fn._cache_size())


def _auto_key(args, kwargs):
    """Fallback variant key when a call site passes none: the hashable
    primitive args (the statics — ints/bools/strs — that split jit
    executables), in position order. Model objects / configs are
    deliberately excluded: they select the OWNER, not the variant."""
    prim = (int, bool, float, str, bytes, type(None), tuple, frozenset)
    key = [a for a in args if isinstance(a, prim)]
    key += [v for _, v in sorted(kwargs.items()) if isinstance(v, prim)]
    return tuple(key)


def compile_contract(name: str, *, max_variants: Optional[int] = None,
                     collectives: Optional[Mapping[str, FrozenSet[str]]]
                     = None,
                     tmp_bytes_budget: Optional[int] = None,
                     allow_host_callbacks: bool = False,
                     allow_f64: bool = False, notes: str = ""):
    """Decorator for a jitted-entry-point BUILDER: registers the
    contract and makes every builder invocation record a variant.

    The wrapped builder accepts three extra keyword-only knobs, all
    popped before the real builder runs:
    - `contract_key`: the variant identity (defaults to the hashable
      primitive args — the jit statics);
    - `contract_owner`: whose budget the mint counts against (an engine
      instance, a trainer; None = module-global);
    - `contract_budget`: a config-derived budget tighter than the
      declared `max_variants` (the engine's pow2 bucket math).
    """

    def deco(builder):
        contract = CompileContract(
            name=name, max_variants=max_variants, collectives=collectives,
            tmp_bytes_budget=tmp_bytes_budget,
            allow_host_callbacks=allow_host_callbacks, allow_f64=allow_f64,
            notes=notes)
        register_contract(contract, builder)

        @functools.wraps(builder)
        def wrapped(*args, contract_key=None, contract_owner=None,
                    contract_budget=None, **kwargs):
            fn = builder(*args, **kwargs)
            record_variant(
                name,
                contract_key if contract_key is not None
                else _auto_key(args, kwargs),
                owner=contract_owner, budget=contract_budget)
            return fn

        wrapped.contract = contract
        wrapped.__contract_builder__ = builder
        return wrapped

    return deco
