"""Static analysis for JAX trace discipline (ISSUE 7).

Two passes, one gate:

- `lint` (analysis/lint.py): an AST linter with JAX-specific rules —
  tracer host-syncs inside jitted code, jit construction in loops,
  unhashable statics, host entropy in traced code, order-unstable
  pytree construction, host syncs in the engine/trainer hot loops,
  and bare `jax.jit` entry points that bypass the contract registry.
  Accepted findings live in `lint_baseline.json` with per-finding
  justifications.
- `contracts` + `audit` (analysis/contracts.py, analysis/audit.py):
  every jitted entry point registers a `@compile_contract` declaring
  its variant budget (how many executables traffic may mint), its
  collective inventory per mesh shape, and its compiled temp-memory
  budget; the auditor AOT-lowers each on a CPU mesh and checks the
  lowered artifact against the declaration — the pjit-on-TPUv4 /
  EQuARX discipline of auditing the compiled collective inventory
  rather than inferring it.

`tools/graft_check.py` is the CLI gate over both passes.

This package must stay importable WITHOUT jax: the contract registry
is bookkeeping (inference/engine.py imports it on every engine), and
the linter is pure `ast`. Only analysis/audit.py touches jax, lazily.
"""

from megatron_llm_tpu.analysis.contracts import (  # noqa: F401
    CompileContract,
    ContractViolation,
    compile_contract,
    get_contract,
    record_variant,
    release_variant,
    register_contract,
    total_live_variants,
    variant_count,
    variants,
)
