"""HLO collective-overlap introspection (ISSUE 12).

The overlap scheduling work (--overlap_grad_reduce /
--overlap_param_gather / --async_pipeline_dispatch) makes a claim about
the COMPILED schedule: collectives run concurrently with compute. This
module measures that claim from post-optimization HLO text the same way
the audit reads collective inventories — from the artifact, never from
the source.

Two layers of evidence, because the two backends give different
visibility:

1. **Async pairs** (`-start`/`-done`): a backend with asynchronous
   collectives (TPU; GPU) splits each overlapped collective into a
   start/done pair and the scheduler moves compute between them. We
   parse the pairs, count the compute ops scheduled between each
   start and its done, and track the maximum number of simultaneously
   in-flight collectives. This XLA build's CPU backend emits NO async
   collectives (every collective is one synchronous op) — on CPU the
   pair count is a MEASURED 0, which is what the MULTICHIP rows'
   `async_collective_pairs` now reports (previously an honest-0
   placeholder, now an honest-0 measurement on CPU and a real count
   the moment the same row runs on TPU).

2. **Schedule interleaving of sync collectives**: post-optimization
   CPU modules are scheduled (`is_scheduled=true` — textual order IS
   execution order), so even without async pairs we can pin the
   STRUCTURAL property the TPU scheduler needs: collectives
   interleaved with heavy compute instead of clumped after it. For
   the backward-interleaved reduce-scatter the signature is while-ops
   (the per-group backward layer scans) BETWEEN consecutive
   reduce-scatters; the eager path reduces everything after the one
   monolithic backward, so its reduce-scatters sit in a compute-free
   clump. graft-check pins exactly this contrast
   (analysis/audit.py `_check_overlap_schedule`).

Heavy ops are `while` (the layer-scan loops — forward, backward, and
remat recompute all live in them), `dot`, and `convolution` — data
movement (copies, bitcasts, packing/unpacking fusions, elementwise
optimizer fusions) is deliberately NOT counted, so the reshapes between
two collectives do not masquerade as hidden compute.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = [
    "CollectiveOverlapReport",
    "collective_overlap_report",
    "parse_computations",
]

# collective opcode families, sync and async forms
COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

_NAME_RE = re.compile(r"^\s*(?:ROOT\s+)?%(?P<name>[^\s=]+)\s*=")
# the opcode token is the one immediately followed by the operand list;
# matching it directly (instead of splitting name/type/opcode) survives
# tuple-typed async ops and TPU layout annotations inside shapes
_COLL_RE = re.compile(
    r"\b(?P<kind>" + "|".join(re.escape(c) for c in COLLECTIVES)
    + r")(?P<form>-start|-done)?\(")
_HEAVY_RE = re.compile(r"\b(?:while|dot|convolution)\(")
# computation headers: `%name (params) -> type {` / `ENTRY %name ...`.
# The param list may contain TUPLE-typed params (while-loop body/cond
# regions: `(arg_tuple.9: (s32[], f32[4,4]))`), so the name is matched
# up to the first paren and the `->`/trailing `{` are checked
# separately — a `[^)]*\)` param matcher would stop at the inner tuple
# and silently drop exactly the computations that carry the scan
# collectives.
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?(?P<name>[^\s(]+)\s*\(")
_OPERAND_RE = re.compile(r"%([^\s,)]+)")


@dataclass
class _Instr:
    name: str
    kind: Optional[str]   # collective family, or None
    form: Optional[str]   # "sync" | "start" | "done" | None
    heavy: bool
    operands: Tuple[str, ...]


@dataclass
class CollectiveOverlapReport:
    """What the schedule says about collective/compute concurrency."""

    # async evidence (-start/-done): pair count, max simultaneously
    # in-flight, and per-pair compute ops between start and done
    async_pairs: int = 0
    max_in_flight: int = 0
    ops_between_pairs: List[int] = field(default_factory=list)
    # sync evidence: per collective kind, op count and the number of
    # heavy ops scheduled between consecutive ops of that kind
    collective_counts: Dict[str, int] = field(default_factory=dict)
    compute_between: Dict[str, List[int]] = field(default_factory=dict)

    @property
    def min_ops_between_pairs(self) -> Optional[int]:
        return min(self.ops_between_pairs) if self.ops_between_pairs \
            else None

    def interleaved(self, kind: str) -> bool:
        """>= 1 heavy compute op scheduled between two collectives of
        `kind` — the sync-schedule witness of per-bucket issue points
        threaded through the backward."""
        return any(n > 0 for n in self.compute_between.get(kind, []))

    def to_dict(self) -> dict:
        return {
            "async_pairs": self.async_pairs,
            "max_in_flight": self.max_in_flight,
            "min_ops_between_pairs": self.min_ops_between_pairs,
            "collective_counts": dict(self.collective_counts),
            "compute_between": {k: list(v)
                                for k, v in self.compute_between.items()},
        }


def parse_computations(hlo_text: str) -> Dict[str, List[_Instr]]:
    """Split post-optimization HLO text into computations, each a list
    of instructions in textual = scheduled order (post-optimization
    modules carry `is_scheduled=true`)."""
    comps: Dict[str, List[_Instr]] = {}
    cur: Optional[str] = None
    for line in hlo_text.splitlines():
        if not line.strip():
            continue
        if not line.startswith((" ", "\t", "}")):
            m = _COMP_RE.match(line.strip())
            if m and "->" in line and line.rstrip().endswith("{"):
                cur = m.group("name")
                comps[cur] = []
            else:
                # an unrecognized top-level line (module header etc.)
                # must CLOSE the current computation — otherwise the
                # next computation's instructions would be misattributed
                # to the previous one and gaps counted across bodies
                cur = None
            continue
        if cur is None:
            continue
        nm = _NAME_RE.match(line)
        if not nm:
            continue
        # only the FIRST collective token followed by "(" is the opcode;
        # operand names like %all-gather-start.1 are never followed by a
        # paren, and metadata op_name strings use underscores
        cm = _COLL_RE.search(line)
        kind = form = None
        if cm:
            kind = cm.group("kind")
            form = {"-start": "start", "-done": "done",
                    None: "sync"}[cm.group("form")]
        rest = line[nm.end():]
        comps[cur].append(_Instr(
            name=nm.group("name"),
            kind=kind,
            form=form,
            heavy=kind is None and bool(_HEAVY_RE.search(line)),
            operands=tuple(_OPERAND_RE.findall(rest)),
        ))
    return comps


def collective_overlap_report(hlo_text: str) -> CollectiveOverlapReport:
    """Measure collective/compute concurrency evidence across every
    computation of a scheduled post-optimization HLO module."""
    rep = CollectiveOverlapReport()
    for instrs in parse_computations(hlo_text).values():
        open_starts: Dict[str, int] = {}  # name -> heavy ops since start
        last_sync_pos: Dict[str, int] = {}  # kind -> heavy ops seen at
        heavy_seen = 0
        for ins in instrs:
            if ins.heavy:
                heavy_seen += 1
                for k in open_starts:
                    open_starts[k] += 1
            if ins.kind is None:
                continue
            if ins.form == "start":
                open_starts[ins.name] = 0
                rep.async_pairs += 1
                rep.max_in_flight = max(rep.max_in_flight,
                                        len(open_starts))
            elif ins.form == "done":
                for op in ins.operands:
                    if op in open_starts:
                        rep.ops_between_pairs.append(open_starts.pop(op))
                        break
            else:  # sync collective
                rep.collective_counts[ins.kind] = \
                    rep.collective_counts.get(ins.kind, 0) + 1
                if ins.kind in last_sync_pos:
                    rep.compute_between.setdefault(ins.kind, []).append(
                        heavy_seen - last_sync_pos[ins.kind])
                last_sync_pos[ins.kind] = heavy_seen
    return rep
