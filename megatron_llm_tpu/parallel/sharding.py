"""Parameter sharding rules — the TPU analogue of the reference's
Column/RowParallelLinear partitioning (ref: core/tensor_parallel/layers.py:
410,566 and VocabParallelEmbedding :128).

Instead of per-layer wrapper modules issuing collectives, each weight gets a
`PartitionSpec` over the (data, stage, model) mesh and GSPMD materialises the
same communication pattern:

- column-parallel (wqkv, mlp w1): output dim sharded over `model`
  (identity fwd / psum bwd conjugate pair, ref: mappings.py:127-141)
- row-parallel (wo, mlp w2): input dim sharded over `model`
  (psum fwd / identity bwd, ref: mappings.py:143-157)
- vocab-parallel (embedding, lm_head): vocab dim over `model`
- norms / small biases: replicated (their grads are psum'd by GSPMD, the
  analogue of the SP layernorm-grad allreduce, ref: optimizer.py:257-277)

ZeRO-1 optimizer-state sharding (ref: distrib_optimizer.py) adds the `data`
axis to the largest divisible free axis of each state leaf.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, ParallelContext


def param_specs(cfg, params: dict) -> dict:
    """PartitionSpec pytree matching a language-model param tree (GPT/
    Llama/Falcon, BERT incl. heads, T5 incl. decoder, and biencoder
    query/context/shared towers). Unknown leaves default to replicated."""

    def layer_specs(layers: dict) -> dict:
        specs: dict = {
            "input_norm": jax.tree.map(lambda _: P(), layers["input_norm"]),
            "attention": {},
            "mlp": {},
        }
        attn = {"wqkv": P(None, None, MODEL_AXIS), "wo": P(None, MODEL_AXIS, None)}
        if "bqkv" in layers["attention"]:
            attn["bqkv"] = P(None, MODEL_AXIS)
            attn["bo"] = P(None, None)
        specs["attention"] = attn
        if cfg.glu_activation:
            mlp = {"w1": P(None, None, None, MODEL_AXIS), "w2": P(None, MODEL_AXIS, None)}
            if "b1" in layers["mlp"]:
                mlp["b1"] = P(None, None, MODEL_AXIS)
                mlp["b2"] = P(None, None)
        else:
            mlp = {"w1": P(None, None, MODEL_AXIS), "w2": P(None, MODEL_AXIS, None)}
            if "b1" in layers["mlp"]:
                mlp["b1"] = P(None, MODEL_AXIS)
                mlp["b2"] = P(None, None)
        specs["mlp"] = mlp
        if "cross_attention" in layers:
            # T5 decoder: q/kv column-parallel, output row-parallel
            # (ref: ParallelAttention cross_attn transformer.py:331-354)
            cross = {
                "wq": P(None, None, MODEL_AXIS),
                "wkv": P(None, None, MODEL_AXIS),
                "wo": P(None, MODEL_AXIS, None),
            }
            if "bq" in layers["cross_attention"]:
                cross["bq"] = P(None, MODEL_AXIS)
                cross["bkv"] = P(None, MODEL_AXIS)
                cross["bo"] = P(None, None)
            specs["cross_attention"] = cross
        for name in ("post_attention_norm", "mlp_norm", "post_cross_norm"):
            if name in layers:
                specs[name] = jax.tree.map(lambda _: P(), layers[name])
        return specs

    def tower_specs(tree: dict) -> dict:
        specs: dict = {}
        for key, val in tree.items():
            if key in ("layers", "decoder_layers"):
                specs[key] = layer_specs(val)
            elif key == "embedding":
                emb = {"word_embeddings": P(MODEL_AXIS, None)}
                for name in ("position_embeddings", "tokentype_embeddings"):
                    if name in val:
                        emb[name] = P(None, None)
                specs[key] = emb
            elif key == "lm_head" and not isinstance(val, dict):
                specs[key] = P(None, MODEL_AXIS)
            elif key == "lm_head" and isinstance(val, dict):
                # BertLMHead: dense replicated, vocab bias model-sharded
                specs[key] = jax.tree.map(lambda _: P(), val)
                specs[key]["bias"] = P(MODEL_AXIS)
            elif key == "lm_head_bias":
                specs[key] = P(MODEL_AXIS)
            else:
                # norms, pooler, binary_head, projections: replicated
                specs[key] = jax.tree.map(lambda _: P(), val)
        return specs

    if set(params) <= {"query", "context", "shared"}:  # biencoder towers
        return {k: tower_specs(v) for k, v in params.items()}
    return tower_specs(params)


def param_shardings(ctx: ParallelContext, cfg, params: dict) -> dict:
    return jax.tree.map(
        lambda spec: NamedSharding(ctx.mesh, spec),
        param_specs(cfg, params),
        is_leaf=lambda x: isinstance(x, P),
    )


def zero1_axis(spec: P, shape: tuple, dp: int,
               skip_leading: bool = False) -> Optional[int]:
    """The leaf axis ZeRO-1 shards over `data`: the first free axis
    divisible by dp, or None when no such axis exists (the replicated
    residue — see zero1_spec). The ONE divisibility rule: zero1_spec,
    the explicit reduce-scatter plan (optimizer/zero1.py), and the audit
    all derive from this so they can never disagree on which leaves are
    sharded.

    `skip_leading` (the --overlap_grad_reduce layout, ISSUE 12): never
    pick axis 0. Stacked (L, ...) layer leaves must shard WITHIN a
    layer for the backward-interleaved reduce-scatter — a layer group's
    psum_scatter can only deliver rank r a same-position block of every
    rank's slice, so sharding the layer axis would interleave shard
    ownership across groups and break the contiguous zero1_spec layout
    the m/v trees are stored in. Skipping axis 0 makes every group's
    scatter land exactly on rows [lo:hi) of the rank's shard. A leaf
    whose ONLY dp-divisible axis is the leading one falls to the
    replicated residue under this rule (its optimizer state replicates
    — the same trade zero1_spec documents for norm scales)."""
    parts = list(spec) + [None] * (len(shape) - len(spec))
    for i, (p, n) in enumerate(zip(parts, shape)):
        if skip_leading and i == 0:
            continue
        if p is None and n % dp == 0 and n >= dp:
            return i
    return None


def zero1_spec(spec: P, shape: tuple, dp: int,
               skip_leading: bool = False) -> P:
    """Add the `data` axis to the first free axis divisible by dp — the
    GSPMD form of the reference's flat-buffer range sharding
    (ref: distrib_optimizer.py:63-116). Unlike the reference, shards respect
    param boundaries; XLA still emits reduce-scatter/all-gather.

    DOCUMENTED DEVIATION (VERDICT r4 weak #7): leaves with NO free axis
    divisible by dp (norm scales, biases — O(h) each) keep replicated
    optimizer state, where the reference's boundary-ignoring flat buffer
    shards every byte. For transformer-shaped models the replicated
    residue is O(layers * h) floats against O(params/dp) sharded — e.g.
    Llama-2-7B at dp=8: ~0.9 MB replicated vs ~3.4 GB/device sharded
    moments (<0.03%). The trade buys per-leaf resharding on restore (the
    checkpoint is mesh-shape-free) and no gather/scatter bookkeeping."""
    k = zero1_axis(spec, shape, dp, skip_leading=skip_leading)
    if k is None:
        return spec
    parts = list(spec) + [None] * (len(shape) - len(spec))
    parts[k] = DATA_AXIS
    return P(*parts)


def _under_layer_stack(path) -> bool:
    """Whether a tree path points inside a stacked-layer subtree (the
    leaves whose leading axis is the layer axis)."""
    for entry in path:
        key = getattr(entry, "key", None)
        if key in ("layers", "decoder_layers"):
            return True
    return False


def optimizer_state_specs(cfg, params: dict, dp: int, distributed: bool,
                          base_specs: Any = None,
                          overlap_grads: bool = False) -> Any:
    """Specs for one params-shaped moment tree (m or v). `base_specs`
    overrides the default param specs (e.g. the pipeline variant with the
    layer axis on `stage`). `overlap_grads` (--overlap_grad_reduce,
    ISSUE 12) applies the skip-leading rule to stacked-layer leaves so
    the m/v layout matches the grads the backward-interleaved
    reduce-scatter delivers (see zero1_axis)."""
    specs = base_specs if base_specs is not None else param_specs(cfg, params)
    if not distributed or dp <= 1:
        return specs
    flat_params, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_specs, treedef = jax.tree.flatten(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    out = [
        zero1_spec(
            s, p.shape, dp,
            skip_leading=overlap_grads and _under_layer_stack(path))
        for s, (path, p) in zip(flat_specs, flat_params)
    ]
    return jax.tree.unflatten(treedef, out)


def kv_pool_axis(shape: tuple, tp: int) -> Optional[int]:
    """The leaf axis the tp-sharded serving engine shards a paged KV
    pool over `model`: the GROUP axis — index 2 of both the
    (num_pages, page_size, g, d) data pools and the (num_pages,
    page_size, g) int8 scale pools — when it divides by tp, else None
    (replicated). The ONE divisibility rule for serving pools, the
    zero1_axis idiom applied to the KV cache: kv_pool_spec, the
    engine's pool allocation (inference/engine.py), and the tp2 audit
    rows (analysis/audit.py) all derive from this so they can never
    disagree on which pool leaves are sharded. Pages and page offsets
    stay unsharded on purpose — the page table is a replicated
    host-trivial scalar-prefetch operand, so every chip addresses the
    same page ids and only the per-(group) blocks it owns."""
    if tp <= 1:
        return None
    if len(shape) < 3 or shape[2] % tp != 0 or shape[2] < tp:
        return None
    return 2


def kv_pool_spec(shape: tuple, tp: int) -> P:
    """PartitionSpec for one paged-pool leaf under serving tp (see
    kv_pool_axis): group axis over `model`, everything else —
    num_pages, page_size, head_dim — replicated per chip."""
    k = kv_pool_axis(shape, tp)
    if k is None:
        return P()
    parts: list = [None] * len(shape)
    parts[k] = MODEL_AXIS
    return P(*parts)


def decode_param_specs(cfg, dec_params: dict) -> dict:
    """PartitionSpec pytree for the DECODE-layout param tree
    (GPTModel.prepare_decode_params: the stacked (L, ...) layer tree
    split into a tuple of per-layer dicts) — the param_specs rules with
    the leading layer axis removed, for the tp-sharded serving engine
    (inference/engine.py serving_tp > 1):

    - wqkv / b1 (glu (2, f)) column-parallel: output dim over `model`
    - wo / w2 row-parallel: input dim over `model`
    - w1 in the UNFLATTENED (h, 2, f) GLU layout: f over `model`. The
      single-chip decode flatten to (h, 2f) concatenates [gate | up]
      along the sharded axis, so a contiguous model split would hand
      chip 0 all gates and chip 1 all ups and force a reshard before
      the elementwise GLU — tp engines keep the training layout
      (prepare_decode_params(flatten_glu=False)).
    - embedding / lm_head vocab-parallel; norms and small biases
      replicated (same rules as param_specs).
    """

    def layer(tree: dict) -> dict:
        specs: dict = {
            "input_norm": jax.tree.map(lambda _: P(), tree["input_norm"]),
        }
        attn = {"wqkv": P(None, MODEL_AXIS), "wo": P(MODEL_AXIS, None)}
        if "bqkv" in tree["attention"]:
            attn["bqkv"] = P(MODEL_AXIS)
            attn["bo"] = P(None)
        specs["attention"] = attn
        w1 = tree["mlp"]["w1"]
        if cfg.glu_activation:
            assert getattr(w1, "ndim", 3) == 3, (
                "tp-sharded decode params need the UNFLATTENED (h, 2, f) "
                "GLU layout (prepare_decode_params(flatten_glu=False)): "
                "the flat (h, 2f) layout concatenates gate|up along the "
                "axis tp would shard")
            mlp = {"w1": P(None, None, MODEL_AXIS),
                   "w2": P(MODEL_AXIS, None)}
            if "b1" in tree["mlp"]:
                mlp["b1"] = P(None, MODEL_AXIS)
                mlp["b2"] = P(None)
        else:
            mlp = {"w1": P(None, MODEL_AXIS), "w2": P(MODEL_AXIS, None)}
            if "b1" in tree["mlp"]:
                mlp["b1"] = P(MODEL_AXIS)
                mlp["b2"] = P(None)
        specs["mlp"] = mlp
        for name in ("post_attention_norm", "mlp_norm"):
            if name in tree:
                specs[name] = jax.tree.map(lambda _: P(), tree[name])
        return specs

    specs: dict = {}
    for key, val in dec_params.items():
        if key == "layers":
            specs[key] = tuple(layer(l) for l in val)
        elif key == "embedding":
            emb = {"word_embeddings": P(MODEL_AXIS, None)}
            for name in ("position_embeddings", "tokentype_embeddings"):
                if name in val:
                    emb[name] = P(None, None)
            specs[key] = emb
        elif key == "lm_head" and not isinstance(val, dict):
            specs[key] = P(None, MODEL_AXIS)
        else:
            specs[key] = jax.tree.map(lambda _: P(), val)
    return specs


def decode_param_shardings(ctx: ParallelContext, cfg,
                           dec_params: dict) -> dict:
    return jax.tree.map(
        lambda spec: NamedSharding(ctx.mesh, spec),
        decode_param_specs(cfg, dec_params),
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_specs() -> P:
    """(batch, seq) host batch: batch dim over data axis."""
    return P(DATA_AXIS, None)
