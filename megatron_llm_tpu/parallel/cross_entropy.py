"""Vocab-parallel cross entropy.

Parity target: ref megatron/core/tensor_parallel/cross_entropy.py:14-143 —
the reference hand-writes allreduce(max), masked target-logit gather,
allreduce(sum_exp) and a custom backward. On TPU the same dataflow is
expressed once in jnp: with logits sharded over the model axis on the vocab
dim, XLA's GSPMD lowers the max/sum reductions to psum over ICI and AD
derives the backward. An explicit `shard_map` variant is provided for when
manual control is wanted; both match the reference's math including
label smoothing (ref :71-87).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu.parallel.mesh import (
    MODEL_AXIS,
    get_context,
    shard_map as _shard_map,
)


def cross_entropy(
    logits: jnp.ndarray,  # (..., vocab), any float dtype
    targets: jnp.ndarray,  # (...), int
    label_smoothing: float = 0.0,
) -> jnp.ndarray:
    """Per-token CE loss in fp32 (ref: _VocabParallelCrossEntropy.forward)."""
    logits = logits.astype(jnp.float32)
    logits_max = jnp.max(logits, axis=-1, keepdims=True)
    shifted = logits - jax.lax.stop_gradient(logits_max)
    sum_exp = jnp.sum(jnp.exp(shifted), axis=-1)
    log_z = jnp.log(sum_exp)
    target_logit = jnp.take_along_axis(
        shifted, targets[..., None], axis=-1
    ).squeeze(-1)
    loss = log_z - target_logit
    if label_smoothing > 0.0:
        # ref :71-87: smoothed loss mixes in mean log-prob over the vocab
        vocab = logits.shape[-1]
        smoothing = label_smoothing * vocab / (vocab - 1)
        mean_log_prob = jnp.mean(shifted, axis=-1) - log_z
        loss = (1.0 - smoothing) * loss - smoothing * mean_log_prob
    return loss


def _ce_shard(logits, targets, vocab_per_shard, label_smoothing):
    """Per-shard body: local max/sum-exp + masked target gather, psum'd
    (mirrors ref cross_entropy.py:20-95 collective-for-collective)."""
    rank = jax.lax.axis_index(MODEL_AXIS)
    logits = logits.astype(jnp.float32)
    local_max = jnp.max(logits, axis=-1)
    # max is a numerical-stability shift only — no gradient flows through it
    # (the GSPMD path stop_gradients it too; pmax has no VJP rule)
    global_max = jax.lax.pmax(jax.lax.stop_gradient(local_max), MODEL_AXIS)
    shifted = logits - global_max[..., None]
    exp = jnp.exp(shifted)
    sum_exp = jax.lax.psum(jnp.sum(exp, axis=-1), MODEL_AXIS)
    log_z = jnp.log(sum_exp)

    vocab_start = rank * vocab_per_shard
    local_target = targets - vocab_start
    in_range = (local_target >= 0) & (local_target < vocab_per_shard)
    safe_target = jnp.where(in_range, local_target, 0)
    gathered = jnp.take_along_axis(shifted, safe_target[..., None], axis=-1).squeeze(-1)
    target_logit = jax.lax.psum(jnp.where(in_range, gathered, 0.0), MODEL_AXIS)

    loss = log_z - target_logit
    if label_smoothing > 0.0:
        vocab = vocab_per_shard * jax.lax.psum(1, MODEL_AXIS)
        smoothing = label_smoothing * vocab / (vocab - 1)
        sum_log_prob = jax.lax.psum(jnp.sum(shifted, axis=-1), MODEL_AXIS)
        mean_log_prob = sum_log_prob / vocab - log_z
        loss = (1.0 - smoothing) * loss - smoothing * mean_log_prob
    return loss


def vocab_parallel_cross_entropy(
    logits: jnp.ndarray,
    targets: jnp.ndarray,
    label_smoothing: float = 0.0,
    explicit: bool = False,
) -> jnp.ndarray:
    """CE over vocab-sharded logits.

    Default path: plain jnp under GSPMD (XLA inserts the psums). With
    `explicit=True` and an installed mesh, runs the hand-written shard_map
    version (useful for verifying collective placement)."""
    ctx = get_context()
    if not explicit or ctx is None or ctx.tp == 1:
        return cross_entropy(logits, targets, label_smoothing)
    vocab_per_shard = logits.shape[-1] // ctx.tp
    fn = _shard_map(
        partial(_ce_shard, vocab_per_shard=vocab_per_shard,
                label_smoothing=label_smoothing),
        mesh=ctx.mesh,
        in_specs=(P("data", None, MODEL_AXIS), P("data", None)),
        out_specs=P("data", None),
    )
    return fn(logits, targets)
