"""Ring attention — context parallelism over the sequence axis.

The long-context mechanism the reference lacks natively (its answer is
sequence parallelism + selective recompute; ring/context parallelism is
the Megatron-Core successor feature). Design follows the blockwise-ring
formulation (Liu et al., Ring Attention; the public JAX reference
implementations use the same scan+ppermute shape):

- the sequence axis is sharded over a mesh axis (`cp`): each device holds
  its (b, s/cp, ...) slice of Q, K, V;
- cp steps of a `lax.scan`: each step computes this device's Q block
  against the currently-resident K/V block with an online-softmax update
  (running row-max m, denominator l, accumulator o — the flash-attention
  recurrence across devices), then `ppermute` rotates K/V one hop around
  the ring, so K/V traffic rides neighbour ICI links and overlaps with
  the block matmuls;
- causal masking uses each block's ORIGIN index ((idx - t) mod cp) to
  reconstruct global positions, and blocks entirely above the diagonal
  skip both einsums via `lax.cond` (per-device branch in the manual
  region — ~2x causal FLOP saving);
- every step is `jax.checkpoint`ed: the backward keeps only the rotating
  K/V boundary blocks (total = one full K/V per device, N*2*g*d — tiny
  next to the N^2 score matrix this exists to avoid) and recomputes the
  per-block scores, mirroring the flash backward.

GQA layout matches the rest of the stack: q (b, s, g, qpk, d), k/v
(b, s, g, d), K/V never broadcast-expanded.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def ring_self_attention(q, k, v, axis_name: str, causal: bool = True):
    """Inside a shard_map region with the sequence sharded over
    `axis_name`: exact attention over the GLOBAL sequence.

    q: (b, s_loc, g, qpk, d); k, v: (b, s_loc, g, d) — local slices.
    Returns (b, s_loc, g, qpk, d).
    """
    cp = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s, g, qpk, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    q_pos = idx * s + jnp.arange(s)  # global rows

    def update(k_blk, v_blk, m, l, o, owner):
        """Online-softmax merge of one K/V block into (m, l, o)."""
        k_pos = owner * s + jnp.arange(s)
        scores = jnp.einsum(
            "bsgqd,btgd->bgqst", q, k_blk,
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            masked = (k_pos[None, :] > q_pos[:, None])  # (s, t)
            scores = jnp.where(masked[None, None, None], NEG_INF, scores)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # clamp so fully-masked rows (m_new == NEG_INF) stay finite
        m_safe = jnp.maximum(m_new, NEG_INF / 2)
        p = jnp.exp(scores - m_safe[..., None])
        if causal:
            p = jnp.where(masked[None, None, None], 0.0, p)
        corr = jnp.exp(m - m_safe)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bgqst,btgd->bgqsd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        return m_new, l, o

    def step(carry, t):
        k_blk, v_blk, m, l, o = carry
        # rotate K/V one hop around the ring FIRST (neighbour ICI
        # traffic; rotating at step entry means no wasted final rotation)
        perm = [(i, (i + 1) % cp) for i in range(cp)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        # after t rotations this block originated on (idx - t) mod cp
        owner = (idx - t) % cp
        if causal:
            # blocks entirely above the diagonal (owner strictly after this
            # device in global order) contribute nothing: skip both einsums
            m, l, o = jax.lax.cond(
                owner > idx,
                lambda args: args[2:5],
                lambda args: update(*args),
                (k_blk, v_blk, m, l, o, owner),
            )
        else:
            m, l, o = update(k_blk, v_blk, m, l, o, owner)
        return (k_blk, v_blk, m, l, o), None

    step = jax.checkpoint(step, prevent_cse=False)
    # mark the zero initials device-varying so scan carry types are stable
    pv = lambda x: jax.lax.pcast(x, (axis_name,), to="varying")  # noqa: E731
    m0 = pv(jnp.full((b, g, qpk, s), NEG_INF, jnp.float32))
    l0 = pv(jnp.zeros((b, g, qpk, s), jnp.float32))
    o0 = pv(jnp.zeros((b, g, qpk, s, d), jnp.float32))
    # the resident block (t = 0, owner = idx) merges without any rotation;
    # the scan then covers the remaining cp - 1 ring hops
    m1, l1, o1 = update(k, v, m0, l0, o0, idx)
    (k_f, v_f, m, l, o), _ = jax.lax.scan(
        step, (k, v, m1, l1, o1), jnp.arange(1, cp)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    # (b, g, qpk, s, d) -> (b, s, g, qpk, d)
    return jnp.moveaxis(out, 3, 1).astype(q.dtype)


def make_ring_attention(mesh, cp_axis: str, causal: bool = True,
                        batch_axis=None):
    """Jittable global-array entry: shards the sequence over `cp_axis`
    (and optionally batch over `batch_axis`) and runs the ring.

    q (b, S, g, qpk, d), k/v (b, S, g, d) with S divisible by the cp
    degree. Differentiable; use inside a larger jitted step or alone.
    """
    qspec = P(batch_axis, cp_axis, None, None, None)
    kspec = P(batch_axis, cp_axis, None, None)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(qspec, kspec, kspec),
        out_specs=qspec,
        axis_names={cp_axis} | ({batch_axis} if batch_axis else set()),
    )
    def ring(q, k, v):
        return ring_self_attention(q, k, v, cp_axis, causal=causal)

    return ring
