"""Ring attention — context parallelism over the sequence axis.

The long-context mechanism the reference lacks natively (its answer is
sequence parallelism + selective recompute; ring/context parallelism is
the Megatron-Core successor feature). Design follows the blockwise-ring
formulation (Liu et al., Ring Attention; the public JAX reference
implementations use the same scan+ppermute shape):

- the sequence axis is sharded over a mesh axis (`cp`): each device holds
  its (b, s/cp, ...) slice of Q, K, V;
- cp steps of a `lax.scan`: each step runs the FLASH kernel
  (ops/flash_attention.py — Pallas on TPU, so the per-hop score tile
  lives in VMEM, never HBM) on the currently-resident K/V block and
  merges hops by logsumexp (running row-max m, denominator l,
  accumulator o — the flash recurrence lifted across devices), then
  `ppermute` rotates K/V one hop around the ring, so K/V traffic rides
  neighbour ICI links and overlaps with the block compute;
- causal masking uses each block's ORIGIN index ((idx - t) mod cp) to
  reconstruct global positions, and blocks entirely above the diagonal
  skip both einsums via `lax.cond` (per-device branch in the manual
  region — ~2x causal FLOP saving);
- every step is `jax.checkpoint`ed: the backward keeps only the rotating
  K/V boundary blocks (total = one full K/V per device, N*2*g*d — tiny
  next to the N^2 score matrix this exists to avoid) and recomputes the
  per-block scores, mirroring the flash backward.

GQA layout matches the rest of the stack: q (b, s, g, qpk, d), k/v
(b, s, g, d), K/V never broadcast-expanded.
"""

from __future__ import annotations

import functools

import jax

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu.parallel.mesh import (
    axis_size as _axis_size,
    pcast as _pcast,
    shard_map as _shard_map,
)

NEG_INF = -1e30


def _masked_hop_with_lse(q, k_blk, v_blk, mask):
    """One ring hop with an explicit (b, s_loc, t_loc) mask (True =
    masked): XLA einsum path returning (o, lse) for the logsumexp merge.
    The score block is s_loc x t_loc (per-hop, checkpointed) — the seq^2
    buffer cp exists to avoid never materializes. Packed-document masks
    take this path; a doc-aware Pallas kernel is a future optimization."""
    b, s, g, qpk, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    scores = jnp.einsum("bsgqd,btgd->bgqst", q, k_blk,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(mask[:, None, None], NEG_INF, scores)
    lse = jax.scipy.special.logsumexp(scores, axis=-1)  # (b, g, qpk, s)
    # fully-masked rows: lse = -inf -> weight 0 in the merge
    probs = jnp.exp(scores - jnp.maximum(lse, NEG_INF / 2)[..., None])
    o = jnp.einsum("bgqst,btgd->bsgqd", probs.astype(v_blk.dtype), v_blk)
    return o, jnp.moveaxis(lse, 3, 1)  # lse -> (b, s, g, qpk)


def ring_self_attention(q, k, v, axis_name: str, causal: bool = True,
                        use_pallas: bool | None = None,
                        interpret: bool = False,
                        doc_start=None):
    """Inside a shard_map region with the sequence sharded over
    `axis_name`: exact attention over the GLOBAL sequence.

    q: (b, s_loc, g, qpk, d); k, v: (b, s_loc, g, d) — local slices.
    Returns (b, s_loc, g, qpk, d).

    Each hop runs the FLASH kernel on the resident K/V block (Pallas on
    TPU, XLA fallback elsewhere) and merges hop results via their
    logsumexp — so the (s_loc x s_loc) score matrix is only ever tiled in
    VMEM, never materialized in HBM, and the per-hop compute is the same
    tuned kernel the non-ring path uses. Under the causal ring, the
    resident (t=0) hop is the diagonal block (causal inside), later hops
    are either fully visible (owner < idx: causal=False) or fully masked
    (owner > idx: skipped before any compute).

    `doc_start` (b, s_loc) int32 — GLOBAL index of each local query's
    document start — enables --reset_attention_mask packed-document
    training with the sequence still sharded (VERDICT r4 #5): every hop
    builds its small block-diagonal mask from the hop's global key
    offsets (allowed iff doc_start[i] <= j <= i) and runs the masked-hop
    path above; above-diagonal hops are still skipped outright.
    """
    from megatron_llm_tpu.ops.flash_attention import (
        flash_attention_with_lse,
    )

    cp = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s, g, qpk, d = q.shape
    if doc_start is not None:
        assert causal, "packed-document masks imply causal attention"
        # global positions of this shard's queries
        q_pos = idx * s + jnp.arange(s)

    def merge(carry, k_blk, v_blk, diag: bool, owner=None):
        """Flash the hop, fold its (o, lse) into the running (m, l, o)."""
        m, l, o = carry
        if doc_start is not None:
            k_pos = owner * s + jnp.arange(s)  # hop's global key positions
            hop_mask = (k_pos[None, None, :] > q_pos[None, :, None]) | \
                (k_pos[None, None, :] < doc_start[:, :, None])
            o_h, lse_h = _masked_hop_with_lse(q, k_blk, v_blk, hop_mask)
        else:
            o_h, lse_h = flash_attention_with_lse(
                q, k_blk, v_blk, causal=diag, use_pallas=use_pallas,
                interpret=interpret,
            )
        m_new = jnp.maximum(m, lse_h)
        m_safe = jnp.maximum(m_new, NEG_INF / 2)
        corr = jnp.exp(m - m_safe)
        w = jnp.exp(lse_h - m_safe)  # hop weight: sum exp(s - m_safe)
        l = l * corr + w
        o = o * corr[..., None] + o_h.astype(jnp.float32) * w[..., None]
        return m_new, l, o

    def step(carry, t):
        k_blk, v_blk, m, l, o = carry
        # rotate K/V one hop around the ring FIRST (neighbour ICI
        # traffic; rotating at step entry means no wasted final rotation)
        perm = [(i, (i + 1) % cp) for i in range(cp)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        # after t rotations this block originated on (idx - t) mod cp
        owner = (idx - t) % cp
        if causal:
            # blocks entirely above the diagonal (owner strictly after
            # this device in global order) contribute nothing: skip the
            # kernel entirely; visible blocks attend in full
            m, l, o = jax.lax.cond(
                owner > idx,
                lambda kb, vb, c: c,
                lambda kb, vb, c: merge(c, kb, vb, diag=False,
                                        owner=owner),
                k_blk, v_blk, (m, l, o),
            )
        else:
            m, l, o = merge((m, l, o), k_blk, v_blk, diag=False,
                            owner=owner)
        return (k_blk, v_blk, m, l, o), None

    step = jax.checkpoint(step, prevent_cse=False)
    # mark the zero initials device-varying so scan carry types are stable
    pv = lambda x: _pcast(x, (axis_name,), to="varying")  # noqa: E731
    m0 = pv(jnp.full((b, s, g, qpk), NEG_INF, jnp.float32))
    l0 = pv(jnp.zeros((b, s, g, qpk), jnp.float32))
    o0 = pv(jnp.zeros((b, s, g, qpk, d), jnp.float32))
    # the resident block (t = 0, owner = idx) is the causal diagonal and
    # merges without any rotation; the scan covers the cp - 1 ring hops
    m1, l1, o1 = merge((m0, l0, o0), k, v, diag=causal, owner=idx)
    (k_f, v_f, m, l, o), _ = jax.lax.scan(
        step, (k, v, m1, l1, o1), jnp.arange(1, cp)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)  # already (b, s, g, qpk, d)


def make_ring_attention(mesh, cp_axis: str, causal: bool = True,
                        batch_axis=None, use_pallas: bool | None = None,
                        interpret: bool = False):
    """Jittable global-array entry: shards the sequence over `cp_axis`
    (and optionally batch over `batch_axis`) and runs the ring.

    q (b, S, g, qpk, d), k/v (b, S, g, d) with S divisible by the cp
    degree. Differentiable; use inside a larger jitted step or alone.
    `use_pallas`/`interpret` reach the per-hop flash kernel (CI runs the
    REAL kernel inside the ring via the Pallas interpreter).
    """
    qspec = P(batch_axis, cp_axis, None, None, None)
    kspec = P(batch_axis, cp_axis, None, None)

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(qspec, kspec, kspec),
        out_specs=qspec,
        axis_names={cp_axis} | ({batch_axis} if batch_axis else set()),
    )
    def ring(q, k, v):
        return ring_self_attention(q, k, v, cp_axis, causal=causal,
                                   use_pallas=use_pallas,
                                   interpret=interpret)

    return ring
