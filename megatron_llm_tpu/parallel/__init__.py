from megatron_llm_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
    ParallelContext,
    build_mesh,
    get_context,
    initialize_parallel,
    shard_activation,
    use_mesh,
)
