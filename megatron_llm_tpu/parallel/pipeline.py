"""Pipeline parallelism — shard_map over the `stage` axis + ppermute.

Parity target: ref megatron/schedules.py + p2p_communication.py. The
reference drives 1F1B by hand: per-rank Python loops issuing batched
NCCL isend/irecv (p2p_communication.py:204-231), explicit
deallocate_output_tensor/custom_backward memory hacks (schedules.py:36-88),
and a separate embedding-grad allreduce group between first and last stage
(parallel_state.py:172-199, optimizer.py:203-229).

The TPU design collapses all of that into one differentiable program:

- the stacked layer axis (L, ...) is sharded over `stage`, so each stage
  materialises only its L/pp layers;
- a `lax.scan` over num_micro + pp - 1 ticks rotates activations with
  `lax.ppermute` (the XLA collective-permute that rides ICI);
- reverse-mode AD through the scan yields the backward pipeline (transpose
  of ppermute is the reverse ppermute) — no hand-written backward schedule;
- parameters that enter the shard_map replicated over `stage` (embedding,
  final norm, lm head) get their gradients psum'd across stages by the
  shard_map transpose automatically — which IS the reference's tied
  embedding-grad sync, for free;
- `data`/`model` axes stay in GSPMD "auto" mode inside the region, so TP/SP
  sharding of each stage's compute keeps working unchanged.

Schedule note: AD produces a GPipe-style schedule (all-forward then
all-backward per scan transpose) rather than interleaved 1F1B — but the
thing 1F1B exists to bound (per-stage live activation memory,
schedules.py:606-722) is bounded here differently and harder: by default
every tick body is `jax.checkpoint`ed, so the backward keeps ONLY the
(b, s, h) boundary carry per tick and recomputes stage internals.
`ParallelConfig.pipeline_remat` — the shared named-savepoint policy
vocabulary of models/remat.py ("tick"/"full", "selective", "dots"/
"save_dots", "offload", "none") — trades that memory floor back for
1F1B-class FLOPs when per-stage HBM allows — measured in
docs/PIPELINE_MEMORY.md ("dots" hits the FLOP floor at intermediate
memory). 1F1B keeps <=pp
in-flight stashes of a stage's FULL internal activations (~tens of b*s*h
per layer chunk); this design keeps (num_micro + pp - 1) single-boundary
tensors. For any real depth/width the boundary stash is the smaller
footprint, and raising num_micro to shrink the GPipe bubble stays cheap —
which also removes the need for interleaved/vpp scheduling (that exists to
shrink the bubble when 1F1B memory forbids more microbatches).

MEASURED: docs/PIPELINE_MEMORY.md (tools/pipeline_memory_table.py) —
marginal memory per added microbatch is exactly one boundary carry
(1.0 MB measured vs 1.0 MB modeled at b2/s512/h256), vs ~16 boundary
carries per in-flight microbatch under a 1F1B full stash at the same
width.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu.analysis.contracts import compile_contract
from megatron_llm_tpu.models.norms import apply_norm
from megatron_llm_tpu.models.rope import precompute_rope
from megatron_llm_tpu.models.transformer import transformer_stack
from megatron_llm_tpu.models.language_model import embed_tokens, lm_logits
from megatron_llm_tpu.parallel.cross_entropy import cross_entropy
from megatron_llm_tpu.parallel.mesh import (
    CONTEXT_AXIS,
    STAGE_AXIS,
    ParallelContext,
    shard_map as _shard_map,
    pcast as _pcast,
)


def pipeline_param_specs(cfg, params: dict) -> dict:
    """Param specs with the layer axis sharded over `stage` (the analogue of
    the reference assigning layer ranges to pp ranks,
    ref: transformer.py:845-895 `_get_num_layers` + offset math)."""
    from megatron_llm_tpu.parallel.sharding import param_specs

    specs = param_specs(cfg, params)

    def add_stage(spec: P) -> P:
        parts = list(spec) or [None]
        assert parts[0] is None, "layer axis already sharded"
        parts[0] = STAGE_AXIS
        return P(*parts)

    specs["layers"] = jax.tree.map(
        add_stage, specs["layers"], is_leaf=lambda x: isinstance(x, P)
    )
    return specs


def _boundary_dtype(cfg):
    """Values whose shard_map/pcast transposes emit copy-all-reduces must
    not be bf16 on CPU — XLA-CPU's AllReducePromotion pass crashes cloning
    a copy-bodied all-reduce ("Invalid binary instruction opcode copy").
    TPU keeps bf16 so inter-stage ppermute traffic stays half-width."""
    return jnp.float32 if jax.default_backend() == "cpu" else cfg.compute_dtype


def _mark_varying(cp, aux, rope, batch_ops, layers_local):
    """Mark every operand stage-(and context-)varying up front, while still
    fp32/int32. If a replicated fp32 param is first cast to bf16 and only
    then implicitly pvary'd (by meeting a varying value), the pvary is a
    bf16 copy-bodied all-reduce and XLA-CPU aborts (see _boundary_dtype);
    pcast-then-cast sidesteps it and is a free no-op marker on TPU.

    batch operands enter context-SHARDED when cp>1 (already context-
    varying) — only the stage axis still needs marking on those; stage-
    sharded layer weights are the mirror case (context-invariant)."""
    manual_axes = (STAGE_AXIS, CONTEXT_AXIS) if cp > 1 else (STAGE_AXIS,)
    pv = lambda x: _pcast(x, manual_axes, to="varying")  # noqa: E731
    pv_s = lambda x: _pcast(x, (STAGE_AXIS,), to="varying")  # noqa: E731
    aux = jax.tree.map(pv, aux)
    rope = pv(rope)
    batch_ops = tuple(map(pv_s if cp > 1 else pv, batch_ops))
    if cp > 1:
        layers_local = jax.tree.map(
            lambda x: _pcast(x, (CONTEXT_AXIS,), to="varying"),
            layers_local,
        )
    return manual_axes, aux, rope, batch_ops, layers_local


def _stage_body(cfg, layers_local, hidden, rope_table, mask, position_ids,
                dropout_rng, deterministic, stage, num_stages):
    """Run this stage's layer chunk. layer indices offset by stage
    (ref: vpp/stage offset math transformer.py:1015-1045)."""
    layers_per_stage = jax.tree.leaves(layers_local)[0].shape[0]
    out, _ = transformer_stack(
        layers_local, cfg, hidden, rope_table, mask, position_ids,
        dropout_rng, deterministic,
        layer_offset=stage * layers_per_stage,
    )
    return out


def make_pipelined_loss_fn(model, pcfg, ctx: ParallelContext):
    """loss(params, batch, rng) with the transformer stack pipelined over
    `stage`. `batch` arrays are (num_micro, b, s[, ...]).

    Replaces the reference's forward_backward_pipelining_* schedules
    (schedules.py:253-722): here one jitted function runs the whole
    embed -> stack -> head/CE pipeline INSIDE a scan-over-ticks, and
    jax.grad of it is the pipelined backward.

    Memory design (the reason the reference hand-schedules 1F1B,
    schedules.py:606-722):
    - embedding runs in-tick, so no (num_micro, b, s, h) input buffer —
      only the int32 token batch enters the region;
    - the last stage computes final-norm + logits + CE in-tick under a
      `lax.cond` and banks two SCALARS per microbatch — no
      (num_micro, b, s, V) logits or (num_micro, b, s, h) output buffer;
    - each tick body is `jax.checkpoint`ed: backward keeps only the
      (b, s, h) boundary carry per tick and recomputes stage internals,
      so peak live activations are ticks x b*s*h boundary values — far
      below 1F1B's pp in-flight FULL-chunk stashes for real configs.

    Loss averaging matches the reference: mean over microbatches of each
    microbatch's masked-mean loss (training.py:442-448), not the global
    token-weighted mean.
    """
    cfg = model.cfg
    mesh = ctx.mesh
    num_stages = pcfg.pipeline_parallel_size
    # Async tick dispatch (--async_pipeline_dispatch, ISSUE 12): the
    # stage-ring ppermute decouples from the lockstep tick. The lockstep
    # body is compute -> permute -> carry: the permute's result feeds
    # the very next tick's compute, so XLA must serialize wire and MXU.
    # Async double-buffers the carry: tick T's body issues the permute
    # of tick T-1's OUTPUT (`fly`), which nothing in tick T's compute
    # consumes — the collective-permute and the stage compute are
    # data-independent inside one scan body, exactly what the
    # latency-hiding scheduler needs to overlap them (the MPMD paper's
    # async point-to-point dispatch, still inside the scan-transpose
    # backward — AD of the delayed carry is the same delay in reverse,
    # so the backward ring overlaps too). The price is schedule depth:
    # each hop takes 2 ticks, so fill/drain grows from pp-1 to
    # 2(pp-1) ticks — at num_micro >> pp the bubble cost is small and
    # the per-tick wire hides; at tiny num_micro lockstep wins
    # (docs/GUIDE.md "Collective overlap scheduling"). Per-microbatch
    # math is IDENTICAL (deterministic runs bitwise vs lockstep,
    # tests/test_overlap.py); with dropout the per-tick rng keys map to
    # different ticks — a different but equally valid stream, like the
    # zero1 per-rank dropout note.
    async_dispatch = getattr(pcfg, "async_pipeline_dispatch", False)
    hop = 2 if async_dispatch else 1
    # Context parallelism inside the pipeline: `context` joins `stage` as a
    # manual axis of the SAME shard_map (Shardy rejects a nested manual
    # region whose operands mix free `stage` with manual `context`), the
    # seq dim of every batch operand is context-sharded, and attention runs
    # the ring directly over the manual axis (models/attention.py routes
    # there via in_manual_region()).
    cp = ctx.cp
    if cp > 1:
        assert cfg.attention_dropout == 0.0, (
            "cp>1 pipelined training: ring attention has no dropout path"
        )

    def loss_fn(params, batch, dropout_rng=None):
        tokens = batch["tokens"]  # (num_micro, b, s)
        labels = batch["labels"]
        loss_mask = batch.get("loss_mask")
        position_ids = batch.get("position_ids")
        num_micro, b, s = tokens.shape
        deterministic = dropout_rng is None

        has_rope = cfg.position_embedding_type == "rotary"
        if has_rope:
            rope_table = precompute_rope(
                cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta,
                cfg.rope_scaling_factor,
            )
        else:
            rope_table = jnp.zeros((1,), jnp.float32)  # placeholder operand

        if loss_mask is None:
            loss_mask = jnp.ones((num_micro, b, s), jnp.float32)
        else:
            loss_mask = loss_mask.astype(jnp.float32)
        if position_ids is None:
            position_ids = jnp.broadcast_to(
                jnp.arange(s, dtype=jnp.int32)[None, None], (num_micro, b, s)
            )

        # Everything the in-tick embed + head need, entering the region
        # stage-replicated; the shard_map transpose psums their grads over
        # `stage` — which IS the reference's tied embedding-grad allreduce
        # (parallel_state.py:172-199) for free.
        aux_params = {
            "embedding": params["embedding"],
            "final_norm": params["final_norm"],
        }
        if not cfg.tie_embed_logits:
            aux_params["lm_head"] = params["lm_head"]

        boundary_dtype = _boundary_dtype(cfg)

        def stack_shard(layers_local, aux, toks, lbls, lmask, pids, rope):
            # layers_local: (L/pp, ...); toks/lbls/pids: (num_micro, b, s)
            from megatron_llm_tpu.parallel.mesh import manual_region

            with manual_region():
                return _stack_shard_body(
                    layers_local, aux, toks, lbls, lmask, pids, rope
                )

        def _stack_shard_body(layers_local, aux, toks, lbls, lmask, pids,
                              rope):
            stage = jax.lax.axis_index(STAGE_AXIS)
            # async dispatch: each hop takes `hop` ticks (one in-flight
            # slot per boundary), so fill/drain stretches accordingly
            total = num_micro + hop * (num_stages - 1)
            manual_axes, aux, rope, (toks, lbls, lmask, pids), \
                layers_local = _mark_varying(
                    cp, aux, rope, (toks, lbls, lmask, pids), layers_local
                )
            rope_t = rope if has_rope else None
            # decorrelate dropout draws across context shards (each shard
            # holds different global positions)
            rng_base = dropout_rng
            if dropout_rng is not None and cp > 1:
                rng_base = jax.random.fold_in(
                    dropout_rng, jax.lax.axis_index(CONTEXT_AXIS)
                )

            def head_losses(hidden, lbl_t, lm_t):
                # returns LOCAL (this context shard's) sums; the context
                # psum happens outside the banking lax.cond — a collective
                # inside that cond aborts XLA-CPU (same restructure as the
                # score path's unconditional ppermute; ADVICE r4)
                h = apply_norm(
                    hidden.astype(cfg.compute_dtype), aux["final_norm"], cfg
                )
                logits = lm_logits(aux, cfg, h)
                losses = cross_entropy(logits, lbl_t)
                return jnp.sum(losses * lm_t), jnp.sum(lm_t)

            def tick(carry, t):
                if async_dispatch:
                    state, fly, sums, denoms = carry
                else:
                    state, sums, denoms = carry
                m_in = jnp.clip(t, 0, num_micro - 1)
                toks_t = jax.lax.dynamic_index_in_dim(toks, m_in, 0, False)
                pids_t = jax.lax.dynamic_index_in_dim(pids, m_in, 0, False)
                rng_e = rng_t = None
                if rng_base is not None:
                    rng_e = jax.random.fold_in(rng_base, m_in)
                    rng_t = jax.random.fold_in(
                        rng_base, num_micro + 1 + t * num_stages
                    )
                # in-tick embed: every stage computes the (cheap) gather,
                # only stage 0 consumes it — no (num_micro,b,s,h) buffer
                emb = embed_tokens(aux, cfg, toks_t, pids_t, rng_e,
                                   deterministic).astype(boundary_dtype)
                inp = jnp.where(stage == 0, emb, state).astype(
                    cfg.compute_dtype
                )
                # pids_t carries GLOBAL positions (context-sharded when
                # cp>1): RoPE inside the stage must rotate each seq shard
                # by its global angle, and --reset_position_ids streams
                # carry non-arange positions even at cp=1
                out = _stage_body(cfg, layers_local, inp, rope_t, None,
                                  pids_t, rng_t, deterministic, stage,
                                  num_stages)
                out = out.astype(boundary_dtype)

                # last stage runs head + CE for the microbatch leaving the
                # pipe this tick; other stages skip the head FLOPs entirely
                m_out = jnp.clip(t - hop * (num_stages - 1), 0,
                                 num_micro - 1)
                valid = (stage == num_stages - 1) & \
                    (t >= hop * (num_stages - 1))
                lbl_t = jax.lax.dynamic_index_in_dim(lbls, m_out, 0, False)
                lm_t = jax.lax.dynamic_index_in_dim(lmask, m_out, 0, False)
                zero = _pcast(
                    jnp.float32(0.0), manual_axes, to="varying"
                )
                sum_t, den_t = jax.lax.cond(
                    valid,
                    lambda h: head_losses(h, lbl_t, lm_t),
                    lambda h: (zero, zero),
                    out,
                )
                if cp > 1:
                    # each context shard holds s/cp tokens of the micro-
                    # batch; `valid` is uniform across context shards at a
                    # given stage, so psum of the selected values equals
                    # the old psum-inside-head_losses — without a
                    # collective inside the cond
                    sum_t = jax.lax.psum(sum_t, CONTEXT_AXIS)
                    den_t = jax.lax.psum(den_t, CONTEXT_AXIS)
                sums = jax.lax.dynamic_update_index_in_dim(
                    sums,
                    jnp.where(
                        valid, sum_t,
                        jax.lax.dynamic_index_in_dim(sums, m_out, 0, False),
                    ),
                    m_out, 0,
                )
                denoms = jax.lax.dynamic_update_index_in_dim(
                    denoms,
                    jnp.where(
                        valid, den_t,
                        jax.lax.dynamic_index_in_dim(denoms, m_out, 0, False),
                    ),
                    m_out, 0,
                )
                # rotate stage s -> s+1 (ref: send_forward
                # p2p_communication.py:292; backward of this ppermute is the
                # reverse rotation = send_backward :311)
                ring = [(i, i + 1) for i in range(num_stages - 1)]
                if async_dispatch:
                    # the DELAYED send: permute last tick's output
                    # (`fly`), which this tick's compute never touches —
                    # wire and MXU are independent inside the body, so
                    # the scheduler can run them concurrently; `out`
                    # rides the carry to be sent next tick
                    arrived = jax.lax.ppermute(fly, STAGE_AXIS, ring)
                    return (arrived, out, sums, denoms), None
                state = jax.lax.ppermute(out, STAGE_AXIS, ring)
                return (state, sums, denoms), None

            # Backward memory policy (ParallelConfig.pipeline_remat) —
            # the SAME named-savepoint vocabulary as the single-mesh stack
            # (models/remat.py): "tick"/"full" keeps only the tick-boundary
            # carries and recomputes stage internals (the TPU answer to
            # deallocate_output_tensor + 1F1B's bounded stash,
            # schedules.py:36-88); "selective" keeps the named matmul
            # outputs; "dots"/"save_dots" keeps every dot (1F1B-class
            # FLOPs, intermediate memory); "offload" parks the selective
            # set in pinned host memory; "none" keeps everything
            # (1F1B-class FLOPs, what the reference's no-remat 1F1B pays
            # in memory). Measured: docs/PIPELINE_MEMORY.md.
            from megatron_llm_tpu.models.remat import remat_wrap

            tick = remat_wrap(tick, pcfg.resolved_pipeline_remat)

            # carries become stage-varying inside the loop; mark the zero
            # initials as varying so the scan carry types are stable
            state = _pcast(
                jnp.zeros((b, s // cp, cfg.hidden_size), boundary_dtype),
                manual_axes, to="varying",
            )
            sums0 = _pcast(
                jnp.zeros((num_micro,), jnp.float32), (STAGE_AXIS,),
                to="varying",
            )
            denoms0 = _pcast(
                jnp.zeros((num_micro,), jnp.float32), (STAGE_AXIS,),
                to="varying",
            )
            if async_dispatch:
                fly0 = _pcast(
                    jnp.zeros((b, s // cp, cfg.hidden_size),
                              boundary_dtype),
                    manual_axes, to="varying",
                )
                (_, _, sums, denoms), _ = jax.lax.scan(
                    tick, (state, fly0, sums0, denoms0),
                    jnp.arange(total)
                )
            else:
                (_, sums, denoms), _ = jax.lax.scan(
                    tick, (state, sums0, denoms0), jnp.arange(total)
                )
            # leading stage axis: only the last stage's row is meaningful;
            # the caller slices [-1], one scalar-row transfer from the last
            # stage (the analogue of the last->first stage loss broadcast,
            # ref: text_generation/communication.py:111).
            return sums[None], denoms[None]

        # (num_micro, b, s) batch operands: seq context-sharded when cp>1
        bspec = P(None, None, CONTEXT_AXIS) if cp > 1 else P()
        stack_mapped = _shard_map(
            stack_shard,
            mesh=mesh,
            in_specs=(P(STAGE_AXIS), P(), bspec, bspec, bspec, bspec, P()),
            out_specs=(P(STAGE_AXIS), P(STAGE_AXIS)),
            axis_names={STAGE_AXIS, CONTEXT_AXIS} if cp > 1
            else {STAGE_AXIS},
        )
        sums, denoms = stack_mapped(
            params["layers"], aux_params, tokens.astype(jnp.int32),
            labels.astype(jnp.int32), loss_mask,
            position_ids.astype(jnp.int32), rope_table,
        )
        sums, denoms = sums[-1], denoms[-1]  # (num_micro,)
        # reference averaging: mean of per-microbatch masked means
        # (training.py:442-448)
        return jnp.mean(sums / jnp.maximum(denoms, 1.0))

    return loss_fn


def make_pipelined_score_fn(model, pcfg, ctx: ParallelContext):
    """Forward-only pipelined scoring on a stage-sharded mesh: tokens
    (num_micro, b, s) -> per-token target log-probs (num_micro, b, s-1),
    lp[..., i] = log P(tokens[..., i+1] | tokens[..., :i+1]).

    The pp>1 inference path the reference runs as micro-batched pipelined
    forward (ref: text_generation/forward_step.py:61-73,153-204 +
    score_and_return_on_first_stage generation.py:20-86): stage-sharded
    params stay in place, microbatches stream through GPipe ticks, and the
    last stage banks each leaving microbatch's target log-probs. No AD, no
    remat — this is the serving-time scorer for perplexity/reranking from
    a pp-trained checkpoint without resharding it.

    For token-by-token DECODE from a pp-trained checkpoint use
    `reshard_params_for_inference` + the normal generation engine (KV
    caches and a while_loop don't pipeline; the reference keeps its decode
    non-pipelined on the last stage too, generation.py:89-286).
    """
    cfg = model.cfg
    mesh = ctx.mesh
    num_stages = pcfg.pipeline_parallel_size
    cp = ctx.cp

    def score_fn(params, tokens):
        tokens = tokens.astype(jnp.int32)
        num_micro, b, s = tokens.shape

        has_rope = cfg.position_embedding_type == "rotary"
        if has_rope:
            rope_table = precompute_rope(
                cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta,
                cfg.rope_scaling_factor,
            )
        else:
            rope_table = jnp.zeros((1,), jnp.float32)
        position_ids = jnp.broadcast_to(
            jnp.arange(s, dtype=jnp.int32)[None, None], (num_micro, b, s)
        )

        aux_params = {
            "embedding": params["embedding"],
            "final_norm": params["final_norm"],
        }
        if not cfg.tie_embed_logits:
            aux_params["lm_head"] = params["lm_head"]

        boundary_dtype = _boundary_dtype(cfg)

        def stack_shard(layers_local, aux, toks, pids, rope):
            from megatron_llm_tpu.parallel.mesh import manual_region

            with manual_region():
                return _score_shard_body(layers_local, aux, toks, pids, rope)

        def _score_shard_body(layers_local, aux, toks, pids, rope):
            stage = jax.lax.axis_index(STAGE_AXIS)
            total = num_micro + num_stages - 1
            manual_axes, aux, rope, (toks, pids), layers_local = \
                _mark_varying(cp, aux, rope, (toks, pids), layers_local)
            rope_t = rope if has_rope else None
            s_loc = s // cp

            # targets = tokens shifted left by one; under cp the last local
            # slot needs the NEXT context shard's first token. Computed once
            # here, UNconditionally — a collective inside the banking
            # lax.cond aborts XLA-CPU. The final GLOBAL position has no
            # target (wraparound garbage); the caller drops it.
            if cp > 1:
                first_next = jax.lax.ppermute(
                    toks[:, :, :1], CONTEXT_AXIS,
                    [((i + 1) % cp, i) for i in range(cp)],
                )
                tgts = jnp.concatenate([toks[:, :, 1:], first_next],
                                       axis=-1)
            else:
                tgts = jnp.roll(toks, -1, axis=-1)

            def head_logprobs(hidden, tgt_t):
                h = apply_norm(
                    hidden.astype(cfg.compute_dtype), aux["final_norm"], cfg
                )
                logits = lm_logits(aux, cfg, h)
                lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
                # position i holds log P(target at i+1)
                return jnp.take_along_axis(
                    lp, tgt_t[..., None], axis=-1
                ).squeeze(-1)  # (b, s_loc)

            def tick(carry, t):
                state, banked = carry
                m_in = jnp.clip(t, 0, num_micro - 1)
                toks_t = jax.lax.dynamic_index_in_dim(toks, m_in, 0, False)
                pids_t = jax.lax.dynamic_index_in_dim(pids, m_in, 0, False)
                emb = embed_tokens(aux, cfg, toks_t, pids_t, None,
                                   True).astype(boundary_dtype)
                inp = jnp.where(stage == 0, emb, state).astype(
                    cfg.compute_dtype
                )
                out = _stage_body(cfg, layers_local, inp, rope_t, None,
                                  pids_t, None, True, stage, num_stages)
                out = out.astype(boundary_dtype)

                m_out = jnp.clip(t - (num_stages - 1), 0, num_micro - 1)
                valid = (stage == num_stages - 1) & (t >= num_stages - 1)
                tgt_t = jax.lax.dynamic_index_in_dim(tgts, m_out, 0, False)
                zero = _pcast(
                    jnp.zeros((b, s_loc), jnp.float32), manual_axes,
                    to="varying",
                )
                lp_t = jax.lax.cond(
                    valid,
                    lambda h: head_logprobs(h, tgt_t),
                    lambda h: zero,
                    out,
                )
                banked = jax.lax.dynamic_update_index_in_dim(
                    banked,
                    jnp.where(
                        valid, lp_t,
                        jax.lax.dynamic_index_in_dim(banked, m_out, 0,
                                                     False),
                    ),
                    m_out, 0,
                )
                state = jax.lax.ppermute(
                    out, STAGE_AXIS,
                    [(i, i + 1) for i in range(num_stages - 1)],
                )
                return (state, banked), None

            state = _pcast(
                jnp.zeros((b, s_loc, cfg.hidden_size), boundary_dtype),
                manual_axes, to="varying",
            )
            banked0 = _pcast(
                jnp.zeros((num_micro, b, s_loc), jnp.float32), manual_axes,
                to="varying",
            )
            (_, banked), _ = jax.lax.scan(
                tick, (state, banked0), jnp.arange(total)
            )
            return banked[None]

        bspec = P(None, None, CONTEXT_AXIS) if cp > 1 else P()
        out_bspec = P(STAGE_AXIS, None, None, CONTEXT_AXIS) if cp > 1 \
            else P(STAGE_AXIS)
        stack_mapped = _shard_map(
            stack_shard,
            mesh=mesh,
            in_specs=(P(STAGE_AXIS), P(), bspec, bspec, P()),
            out_specs=out_bspec,
            axis_names={STAGE_AXIS, CONTEXT_AXIS} if cp > 1
            else {STAGE_AXIS},
        )
        banked = stack_mapped(
            params["layers"], aux_params, tokens,
            position_ids, rope_table,
        )
        # only the last stage's bank is real; drop the final position
        # (no target)
        return banked[-1][:, :, :-1]

    return score_fn


def make_pipelined_decode_fn(model, pcfg, ctx: ParallelContext, *,
                             prefill_len: int, max_len: int,
                             num_micro: int | None = None,
                             greedy: bool = True, top_k: int = 0,
                             top_p: float = 0.0, temperature: float = 1.0,
                             vocab_size: int | None = None,
                             termination_id: int | None = None,
                             use_eod_for_early_termination: bool = True,
                             return_log_probs: bool = False):
    """Token-by-token KV-cached decode ON the stage-sharded mesh — no
    `reshard_params_for_inference` pp x param-memory blowup (VERDICT r4
    #4; ref: the pipelined inference forwards of
    text_generation/forward_step.py:153-204).

    Round-robin schedule: the batch is split into `num_micro` (default pp)
    groups; at every tick each stage advances a DIFFERENT group by one
    token, boundaries rotate by `lax.ppermute`, and the last stage samples
    the next token and sends it back to stage 0 — with num_micro == pp the
    returned token arrives exactly when stage 0 next serves that group, so
    steady-state has zero bubble. Each stage holds ONLY its layers' KV
    cache shard: per-device cache AND param memory stay 1/pp.

    Mechanics mirrored from the training/score pipelines: collectives stay
    OUT of lax.conds (XLA-CPU), operands are pcast stage-varying up front,
    and fill/drain garbage ticks write their cache columns into a scratch
    region past max_len (offset redirect) so no per-tick buffer select is
    needed.

    Decode ticks (s == 1) stream each layer's stacked-cache slice through
    the Pallas decode-attention kernel ("tgd" layout, in place — no
    transpose) whenever the scratch-tailed cache length is kernel-
    eligible (models/attention.py routes there; exact-match vs the
    single-mesh engine in tests/test_pp_inference.py), so pp-mesh serving
    gets the same HBM-line-rate attention as the unrolled decode path.
    Prefill chunks (s > 1) keep the batched-GEMM path.

    Returns decode(params, tokens (b, max_len), lengths (b,), rng) ->
    (tokens, gen_lengths, log_probs|None), semantics matching
    `generation.generate_tokens` (greedy path exact).
    """
    from megatron_llm_tpu.inference.generation import select_next_token

    cfg = model.cfg
    mesh = ctx.mesh
    pp = pcfg.pipeline_parallel_size
    assert ctx.cp == 1, "pipelined decode: cp axis unsupported"
    nm = num_micro or pp
    assert nm >= pp, "num_micro must be >= pp (token return latency)"
    steps = max_len - prefill_len - 1  # decode rounds after the seed
    assert steps >= 0
    cache_T = max_len + max(prefill_len, 1)  # scratch tail for garbage ticks
    has_rope = cfg.position_embedding_type == "rotary"

    def decode_fn(params, tokens, lengths, rng=None):
        tokens = tokens.astype(jnp.int32)
        b, _ = tokens.shape
        assert b % nm == 0, (b, nm)
        b_m = b // nm
        toks_g = tokens.reshape(nm, b_m, max_len)
        lens_g = lengths.astype(jnp.int32).reshape(nm, b_m)
        if rng is None:
            rng = jax.random.key(0)
        rng = jax.random.key_data(rng).astype(jnp.uint32)  # pcast-able

        if has_rope:
            rope_table = precompute_rope(
                cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta,
                cfg.rope_scaling_factor,
            )
        else:
            rope_table = jnp.zeros((1,), jnp.float32)

        aux_params = {
            "embedding": params["embedding"],
            "final_norm": params["final_norm"],
        }
        if not cfg.tie_embed_logits:
            aux_params["lm_head"] = params["lm_head"]

        boundary_dtype = _boundary_dtype(cfg)

        def shard(layers_local, aux, toks, lens, rng_u):
            from megatron_llm_tpu.parallel.mesh import manual_region

            with manual_region():
                return _decode_shard_body(layers_local, aux, toks, lens,
                                          rng_u)

        def _decode_shard_body(layers_local, aux, toks, lens, rng_u):
            stage = jax.lax.axis_index(STAGE_AXIS)
            L_loc = jax.tree.leaves(layers_local)[0].shape[0]
            _, aux, rope, (toks, lens, rng_u), _ = _mark_varying(
                1, aux, rope_table, (toks, lens, rng_u), layers_local
            )
            rope_t = rope if has_rope else None
            base_rng = jax.random.wrap_key_data(rng_u)
            pv = lambda x: _pcast(  # noqa: E731
                x, (STAGE_AXIS,), to="varying"
            )

            def head(hidden):  # (b_m, s, h) -> (b_m, s, V) fp32
                h = apply_norm(
                    hidden.astype(cfg.compute_dtype), aux["final_norm"], cfg
                )
                return lm_logits(aux, cfg, h).astype(jnp.float32)

            def run_stage(inp, kc, vc, m, off):
                """One stage pass of (b_m, s) tokens at cache offset
                `off` for microbatch m; returns (out, kc, vc)."""
                kc_m = jax.lax.dynamic_index_in_dim(kc, m, 1, False)
                vc_m = jax.lax.dynamic_index_in_dim(vc, m, 1, False)
                out, new_caches = transformer_stack(
                    layers_local, cfg, inp, rope_t, None, None, None, True,
                    kv_caches={"k": kc_m, "v": vc_m, "offset": off},
                    layer_offset=stage * L_loc,
                )
                kc = jax.lax.dynamic_update_index_in_dim(
                    kc, new_caches["k"], m, 1
                )
                vc = jax.lax.dynamic_update_index_in_dim(
                    vc, new_caches["v"], m, 1
                )
                return out, kc, vc

            kshape = (L_loc, nm, b_m, cache_T, cfg.num_query_groups,
                      cfg.head_dim)
            kc = pv(jnp.zeros(kshape, cfg.compute_dtype))
            vc = pv(jnp.zeros(kshape, cfg.compute_dtype))

            # ---- prefill: GPipe ticks over full-prefix chunks ----------
            pids_prefix = jnp.arange(prefill_len, dtype=jnp.int32)[None]

            def prefill_tick(carry, t):
                state, kc, vc, seeds, lps, toks_b = carry
                m = jnp.clip(t - stage, 0, nm - 1)
                valid = (t >= stage) & (t - stage <= nm - 1)
                chunk = jax.lax.dynamic_index_in_dim(toks, m, 0, False)
                chunk = chunk[:, :prefill_len]
                emb = embed_tokens(aux, cfg, chunk, pids_prefix, None,
                                   True).astype(boundary_dtype)
                inp = jnp.where(stage == 0, emb, state).astype(
                    cfg.compute_dtype
                )
                # garbage ticks redirect their cache writes past max_len
                off = jnp.where(valid, 0, max_len)
                out, kc, vc = run_stage(inp, kc, vc, m, off)
                out = out.astype(boundary_dtype)

                valid_last = (stage == pp - 1) & (t >= pp - 1) & \
                    (t - (pp - 1) <= nm - 1)
                m_out = jnp.clip(t - (pp - 1), 0, nm - 1)
                step_rng = jax.random.fold_in(base_rng, m_out)
                toks_out = jax.lax.dynamic_index_in_dim(toks, m_out, 0,
                                                        False)

                # the head (final norm + full-vocab logits) runs ONLY on
                # the last stage, same lax.cond pattern as the training
                # tick's head_losses — no collectives inside the cond
                def last_stage_work(h):
                    if return_log_probs:
                        logits = head(h)  # (b_m, prefill, V)
                        lp_all = jax.nn.log_softmax(logits, axis=-1)
                        lp_pref = jnp.take_along_axis(
                            lp_all[:, :-1],
                            toks_out[:, 1:prefill_len, None], axis=-1,
                        ).squeeze(-1)  # (b_m, prefill-1)
                        last_logits = logits[:, -1]
                    else:
                        lp_pref = pv(jnp.zeros((b_m, prefill_len - 1),
                                               jnp.float32))
                        last_logits = head(h[:, -1:])[:, 0]
                    # seed token at position prefill_len (teacher-forced
                    # if the row's prompt extends past the prefix)
                    sample = select_next_token(
                        last_logits, toks_out[:, prefill_len - 1],
                        step_rng, jnp.float32(top_p),
                        greedy=greedy, top_k=top_k, top_p=top_p,
                        temperature=temperature, vocab_size=vocab_size,
                    )
                    if prefill_len < max_len:
                        started = jax.lax.dynamic_index_in_dim(
                            lens, m_out, 0, False) <= prefill_len
                        chosen = jnp.where(started, sample,
                                           toks_out[:, prefill_len])
                    else:
                        chosen = sample
                    lp_seed = jnp.take_along_axis(
                        jax.nn.log_softmax(last_logits, -1),
                        chosen[:, None], axis=-1,
                    ).squeeze(-1) if return_log_probs else \
                        pv(jnp.zeros((b_m,), jnp.float32))
                    return chosen, lp_pref, lp_seed

                def skip_stage_work(h):
                    return (pv(jnp.zeros((b_m,), jnp.int32)),
                            pv(jnp.zeros((b_m, prefill_len - 1),
                                         jnp.float32)),
                            pv(jnp.zeros((b_m,), jnp.float32)))

                chosen, lp_pref, lp_seed = jax.lax.cond(
                    valid_last, last_stage_work, skip_stage_work, out
                )
                if return_log_probs:
                    lps = jnp.where(
                        valid_last,
                        jax.lax.dynamic_update_slice(
                            lps, lp_pref[None], (m_out, 0, 0)
                        ),
                        lps,
                    )
                    lps = jnp.where(
                        valid_last,
                        jax.lax.dynamic_update_slice(
                            lps, lp_seed[None, :, None],
                            (m_out, 0, prefill_len - 1),
                        ),
                        lps,
                    )
                seeds = jnp.where(
                    valid_last,
                    jax.lax.dynamic_update_index_in_dim(seeds, chosen,
                                                        m_out, 0),
                    seeds,
                )
                if prefill_len < max_len:
                    toks_b = jnp.where(
                        valid_last,
                        jax.lax.dynamic_update_slice(
                            toks_b, chosen[None, :, None],
                            (m_out, 0, prefill_len),
                        ),
                        toks_b,
                    )
                state = jax.lax.ppermute(
                    out, STAGE_AXIS,
                    [(i, i + 1) for i in range(pp - 1)],
                )
                return (state, kc, vc, seeds, lps, toks_b), None

            state0 = pv(jnp.zeros((b_m, prefill_len, cfg.hidden_size),
                                  boundary_dtype))
            seeds0 = pv(jnp.zeros((nm, b_m), jnp.int32))
            lps0 = pv(jnp.zeros((nm, b_m, max_len - 1), jnp.float32))
            (_, kc, vc, seeds, lps, toks), _ = jax.lax.scan(
                prefill_tick, (state0, kc, vc, seeds0, lps0, toks),
                jnp.arange(nm + pp - 1),
            )
            # ship the seed tokens to stage 0's feed buffer
            next_tok = jax.lax.ppermute(seeds, STAGE_AXIS, [(pp - 1, 0)])

            # ---- decode: round-robin single-token ticks ----------------
            offsets0 = pv(jnp.full((nm,), prefill_len, jnp.int32))
            state0 = pv(jnp.zeros((b_m, 1, cfg.hidden_size),
                                  boundary_dtype))
            # the SEED token (sampled at position prefill_len during
            # prefill) gets the same eod bookkeeping generate_tokens
            # applies to every generated position; seeds are only real on
            # the last stage — the same authority the updates below keep
            if termination_id is not None:
                seed_done = (seeds == termination_id) & \
                    (lens <= prefill_len)
                done0 = seed_done
                glens0 = jnp.where(seed_done, prefill_len + 1, max_len)
            else:
                done0 = pv(jnp.zeros((nm, b_m), bool))
                glens0 = pv(jnp.full((nm, b_m), max_len, jnp.int32))
            total = steps * nm + pp - 1

            def cond(carry):
                t = carry[0]
                all_done = carry[-1]
                keep = t < total
                if termination_id is not None and \
                        use_eod_for_early_termination:
                    keep &= ~all_done
                return keep

            def body(carry):
                (t, state, kc, vc, next_tok, toks_b, lps, done, glens,
                 offsets, _) = carry
                m = jnp.mod(t - stage, nm)
                valid = (t >= stage) & (t - stage < steps * nm)
                off = jax.lax.dynamic_index_in_dim(offsets, m, 0, False)
                tok_in = jax.lax.dynamic_index_in_dim(next_tok, m, 0,
                                                      False)
                emb = embed_tokens(aux, cfg, tok_in[:, None], off[None,
                                   None], None, True).astype(boundary_dtype)
                inp = jnp.where(stage == 0, emb, state).astype(
                    cfg.compute_dtype
                )
                off_w = jnp.where(valid, off, max_len)
                out, kc, vc = run_stage(inp, kc, vc, m, off_w)
                out = out.astype(boundary_dtype)
                offsets = jnp.where(
                    valid,
                    jax.lax.dynamic_update_index_in_dim(offsets, off + 1,
                                                        m, 0),
                    offsets,
                )

                # last stage: sample position off+1's token for its group
                m_l = jnp.mod(t - (pp - 1), nm)
                valid_last = (stage == pp - 1) & (t >= pp - 1) & \
                    (t - (pp - 1) < steps * nm)
                pos = jax.lax.dynamic_index_in_dim(
                    offsets, m_l, 0, False)  # off+1 (just incremented)
                step_rng = jax.random.fold_in(
                    base_rng, pos * nm + m_l
                )
                toks_m = jax.lax.dynamic_index_in_dim(toks_b, m_l, 0,
                                                      False)
                lens_m = jax.lax.dynamic_index_in_dim(lens, m_l, 0, False)
                started = lens_m <= pos

                # full-vocab head + sampling under lax.cond: only the
                # last stage pays the h x V matvec per tick
                def last_stage_work(h):
                    logits = head(h)[:, 0]  # (b_m, V)
                    prev = jnp.take_along_axis(
                        toks_m,
                        jnp.broadcast_to(jnp.maximum(pos - 1, 0),
                                         (b_m,))[:, None],
                        axis=1,
                    ).squeeze(1)
                    sample = select_next_token(
                        logits, prev, step_rng, jnp.float32(top_p),
                        greedy=greedy, top_k=top_k, top_p=top_p,
                        temperature=temperature, vocab_size=vocab_size,
                    )
                    prompt_tok = jnp.take_along_axis(
                        toks_m,
                        jnp.broadcast_to(jnp.minimum(pos, max_len - 1),
                                         (b_m,))[:, None],
                        axis=1,
                    ).squeeze(1)
                    chosen = jnp.where(started, sample, prompt_tok)
                    lp_t = jnp.take_along_axis(
                        jax.nn.log_softmax(logits, -1), chosen[:, None],
                        axis=-1,
                    ).squeeze(-1) if return_log_probs else \
                        pv(jnp.zeros((b_m,), jnp.float32))
                    return chosen, lp_t

                def skip_stage_work(h):
                    return (pv(jnp.zeros((b_m,), jnp.int32)),
                            pv(jnp.zeros((b_m,), jnp.float32)))

                chosen, lp_t = jax.lax.cond(
                    valid_last, last_stage_work, skip_stage_work, out
                )
                new_toks_m = jax.vmap(
                    lambda row, c: jax.lax.dynamic_update_index_in_dim(
                        row, c, jnp.minimum(pos, max_len - 1), 0
                    )
                )(toks_m, chosen)
                toks_b = jnp.where(
                    valid_last,
                    jax.lax.dynamic_update_index_in_dim(
                        toks_b, new_toks_m, m_l, 0
                    ),
                    toks_b,
                )
                if return_log_probs:
                    lps_m = jax.lax.dynamic_index_in_dim(lps, m_l, 0,
                                                         False)
                    new_lps_m = jax.vmap(
                        lambda row, v: jax.lax.dynamic_update_index_in_dim(
                            row, v, jnp.minimum(pos - 1, max_len - 2), 0
                        )
                    )(lps_m, lp_t)
                    lps = jnp.where(
                        valid_last,
                        jax.lax.dynamic_update_index_in_dim(
                            lps, new_lps_m, m_l, 0
                        ),
                        lps,
                    )
                if termination_id is not None:
                    done_m = jax.lax.dynamic_index_in_dim(done, m_l, 0,
                                                          False)
                    glens_m = jax.lax.dynamic_index_in_dim(glens, m_l, 0,
                                                           False)
                    done_token = (chosen == termination_id) & started
                    just = done_token & ~done_m
                    glens_m = jnp.where(just, pos + 1, glens_m)
                    done_m = done_m | done_token
                    done = jnp.where(
                        valid_last,
                        jax.lax.dynamic_update_index_in_dim(done, done_m,
                                                            m_l, 0),
                        done,
                    )
                    glens = jnp.where(
                        valid_last,
                        jax.lax.dynamic_update_index_in_dim(glens, glens_m,
                                                            m_l, 0),
                        glens,
                    )
                    all_done_local = jnp.where(
                        stage == pp - 1, jnp.all(done), False
                    )
                else:
                    all_done_local = jnp.asarray(False)
                # collectives OUTSIDE any cond (XLA-CPU rule)
                all_done = jax.lax.psum(
                    all_done_local.astype(jnp.int32), STAGE_AXIS
                ) > 0
                chosen_bc = jnp.where(valid_last, chosen, 0)
                tok_back = jax.lax.ppermute(chosen_bc, STAGE_AXIS,
                                            [(pp - 1, 0)])
                next_tok = jnp.where(
                    (stage == 0) & (t >= pp - 1),
                    jax.lax.dynamic_update_index_in_dim(
                        next_tok, tok_back, m_l, 0
                    ),
                    next_tok,
                )
                state = jax.lax.ppermute(
                    out, STAGE_AXIS,
                    [(i, i + 1) for i in range(pp - 1)],
                )
                return (t + 1, state, kc, vc, next_tok, toks_b, lps, done,
                        glens, offsets, all_done)

            # all_done comes out of a psum — stage-INVARIANT, so its init
            # must be too
            carry = (jnp.int32(0), state0, kc, vc, next_tok, toks, lps,
                     done0, glens0, offsets0, jnp.asarray(False))
            carry = jax.lax.while_loop(cond, body, carry)
            toks_b, lps, glens = carry[5], carry[6], carry[8]
            return toks_b[None], lps[None], glens[None]

        mapped = _shard_map(
            shard,
            mesh=mesh,
            in_specs=(P(STAGE_AXIS), P(), P(), P(), P()),
            out_specs=(P(STAGE_AXIS), P(STAGE_AXIS), P(STAGE_AXIS)),
            axis_names={STAGE_AXIS},
        )
        toks_out, lps_out, glens_out = mapped(
            params["layers"], aux_params, toks_g, lens_g, rng
        )
        # the last stage's bank is authoritative
        out_tokens = toks_out[-1].reshape(b, max_len)
        out_lens = glens_out[-1].reshape(b)
        out_lps = lps_out[-1].reshape(b, max_len - 1) \
            if return_log_probs else None
        return out_tokens, out_lens, out_lps

    return decode_fn


def reshard_params_for_inference(params, ctx: ParallelContext, cfg):
    """Reshard a stage-sharded param tree to stage-REPLICATED (dp/tp/cp
    sharding kept) so the non-pipelined generation engine can serve it on
    the same mesh. The orbax checkpoint layer already reshards across mesh
    shapes on restore; this is the in-memory equivalent for params that
    are live on a pp>1 mesh. Costs pp x the per-device param memory —
    serving a model too big for that needs the pipelined scorer above or a
    smaller serving mesh."""
    from jax.sharding import NamedSharding

    from megatron_llm_tpu.parallel.sharding import param_specs

    specs = param_specs(cfg, params)
    sh = jax.tree.map(lambda sp: NamedSharding(ctx.mesh, sp), specs,
                      is_leaf=lambda x: isinstance(x, P))
    return jax.device_put(params, sh)


@compile_contract(
    "train.pipeline_step",
    max_variants=8,  # num_microbatches buckets per trainer, like
    # train.step — the trainer passes contract_key=num_microbatches
    collectives=None,  # the per-tick stage ring needs a stage-sharded
    # model to lower (collective-permute + tp all-reduces); the pp
    # suites (test_pipeline, test_sp_memory) exercise the lowering —
    # variants and markers are still contract-audited
    notes="the pp>1 per-tick train step; pipeline_remat policies ride "
          "inside one variant (policy is baked at build time)")
def make_pipelined_train_step(model, tcfg, pcfg, ctx: ParallelContext):
    """train_step(params, opt_state, batch, lr, wd, rng) for pp > 1
    (ref: train_step + get_forward_backward_func, training.py:391-431).
    fp16 loss scaling follows the same protocol as the non-pipelined step
    (see training/train_step.py)."""
    from megatron_llm_tpu.optimizer.optimizer import (
        get_grad_scaler,
        optimizer_step,
    )

    loss_fn = make_pipelined_loss_fn(model, pcfg, ctx)
    scaler = get_grad_scaler(tcfg)

    def train_step(params, opt_state, batch, lr, wd, rng=None,
                   spike_threshold=None):
        loss_scale = (
            scaler.scale(opt_state.scaler) if scaler is not None else None
        )

        def scaled_loss(p, b, r):
            loss = loss_fn(p, b, r)
            if loss_scale is not None:
                return loss * loss_scale, loss
            return loss, loss

        (_, loss), grads = jax.value_and_grad(scaled_loss, has_aux=True)(
            params, batch, rng
        )
        if scaler is not None:
            # unscale; the overflow check rides optimizer_step's grad norm
            inv = 1.0 / loss_scale
            grads = jax.tree.map(lambda g: g * inv, grads)
        found_inf = None
        if spike_threshold is not None:
            # the loss watchdog's in-step skip gate — same contract as
            # the non-pipelined step (training/train_step.py): skips
            # the update; never drives the fp16 scale
            found_inf = ~jnp.isfinite(loss) | (loss > spike_threshold)
        params, opt_state, stats = optimizer_step(
            params, grads, opt_state, tcfg, lr, weight_decay=wd,
            found_inf=found_inf, scaler=scaler,
        )
        stats["loss"] = loss
        return params, opt_state, stats

    return train_step
