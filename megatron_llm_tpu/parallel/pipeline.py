"""Pipeline parallelism — shard_map over the `stage` axis + ppermute.

Parity target: ref megatron/schedules.py + p2p_communication.py. The
reference drives 1F1B by hand: per-rank Python loops issuing batched
NCCL isend/irecv (p2p_communication.py:204-231), explicit
deallocate_output_tensor/custom_backward memory hacks (schedules.py:36-88),
and a separate embedding-grad allreduce group between first and last stage
(parallel_state.py:172-199, optimizer.py:203-229).

The TPU design collapses all of that into one differentiable program:

- the stacked layer axis (L, ...) is sharded over `stage`, so each stage
  materialises only its L/pp layers;
- a `lax.scan` over num_micro + pp - 1 ticks rotates activations with
  `lax.ppermute` (the XLA collective-permute that rides ICI);
- reverse-mode AD through the scan yields the backward pipeline (transpose
  of ppermute is the reverse ppermute) — no hand-written backward schedule;
- parameters that enter the shard_map replicated over `stage` (embedding,
  final norm, lm head) get their gradients psum'd across stages by the
  shard_map transpose automatically — which IS the reference's tied
  embedding-grad sync, for free;
- `data`/`model` axes stay in GSPMD "auto" mode inside the region, so TP/SP
  sharding of each stage's compute keeps working unchanged.

Schedule note: AD produces a GPipe-style schedule (all-forward then
all-backward per scan transpose) rather than interleaved 1F1B; the 1F1B
memory win is recovered with `jax.checkpoint` on the stage body (activation
stash per microbatch = one remat'd layer chunk). A hand-scheduled
1F1B/interleaved variant is a planned optimization (SURVEY.md §7 step 6).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu.models.norms import apply_norm
from megatron_llm_tpu.models.rope import precompute_rope
from megatron_llm_tpu.models.transformer import transformer_stack
from megatron_llm_tpu.models.language_model import embed_tokens, lm_logits
from megatron_llm_tpu.parallel.cross_entropy import cross_entropy
from megatron_llm_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
    ParallelContext,
)


def pipeline_param_specs(cfg, params: dict) -> dict:
    """Param specs with the layer axis sharded over `stage` (the analogue of
    the reference assigning layer ranges to pp ranks,
    ref: transformer.py:845-895 `_get_num_layers` + offset math)."""
    from megatron_llm_tpu.parallel.sharding import param_specs

    specs = param_specs(cfg, params)

    def add_stage(spec: P) -> P:
        parts = list(spec) or [None]
        assert parts[0] is None, "layer axis already sharded"
        parts[0] = STAGE_AXIS
        return P(*parts)

    specs["layers"] = jax.tree.map(
        add_stage, specs["layers"], is_leaf=lambda x: isinstance(x, P)
    )
    return specs


def _stage_body(cfg, layers_local, hidden, rope_table, mask, position_ids,
                dropout_rng, deterministic, stage, num_stages):
    """Run this stage's layer chunk. layer indices offset by stage
    (ref: vpp/stage offset math transformer.py:1015-1045)."""
    layers_per_stage = jax.tree.leaves(layers_local)[0].shape[0]
    out, _ = transformer_stack(
        layers_local, cfg, hidden, rope_table, mask, position_ids,
        dropout_rng, deterministic,
        layer_offset=stage * layers_per_stage,
    )
    return out


def make_pipelined_loss_fn(model, pcfg, ctx: ParallelContext):
    """loss(params, batch, rng) with the transformer stack pipelined over
    `stage`. `batch` arrays are (num_micro, b, s[, ...]).

    Replaces the reference's forward_backward_pipelining_* schedules
    (schedules.py:253-722): here one jitted function does embed -> pipelined
    stack -> head/CE, and jax.grad of it is the full pipelined backward.
    """
    cfg = model.cfg
    mesh = ctx.mesh
    num_stages = pcfg.pipeline_parallel_size

    def loss_fn(params, batch, dropout_rng=None):
        tokens = batch["tokens"]  # (num_micro, b, s)
        labels = batch["labels"]
        loss_mask = batch.get("loss_mask")
        position_ids = batch.get("position_ids")
        num_micro, b, s = tokens.shape
        deterministic = dropout_rng is None

        if cfg.position_embedding_type == "rotary":
            rope_table = precompute_rope(
                cfg.head_dim, cfg.max_position_embeddings, cfg.rope_theta,
                cfg.rope_scaling_factor,
            )
        else:
            rope_table = None

        # ---- embed all microbatches (stage-replicated GSPMD compute) ----
        def embed_micro(toks, pids, rng):
            return embed_tokens(params, cfg, toks, pids, rng, deterministic)

        emb_rngs = None
        if dropout_rng is not None:
            emb_rngs = jax.random.split(
                jax.random.fold_in(dropout_rng, 0), num_micro
            )
        hidden_micro = jax.vmap(embed_micro)(
            tokens,
            position_ids
            if position_ids is not None
            else jnp.broadcast_to(jnp.arange(s)[None, None], (num_micro, 1, s)),
            emb_rngs,
        )  # (num_micro, b, s, h)

        # ---- pipelined stack over `stage` ------------------------------
        # Boundary/carry dtype: values whose shard_map/pcast transposes emit
        # copy-all-reduces must not be bf16 on CPU — XLA-CPU's
        # AllReducePromotion pass crashes cloning a copy-bodied all-reduce
        # ("Invalid binary instruction opcode copy"). TPU keeps bf16 so the
        # inter-stage ppermute traffic stays half-width.
        boundary_dtype = (
            jnp.float32 if jax.default_backend() == "cpu" else cfg.compute_dtype
        )

        def stack_shard(layers_local, hidden_mb):
            # layers_local: (L/pp, ...); hidden_mb: (num_micro, b, s, h)
            from megatron_llm_tpu.parallel.mesh import manual_region

            with manual_region():
                out = _stack_shard_body(
                    layers_local, hidden_mb.astype(boundary_dtype)
                )
            return out.astype(jnp.float32)

        def _stack_shard_body(layers_local, hidden_mb):
            stage = jax.lax.axis_index(STAGE_AXIS)
            total = num_micro + num_stages - 1
            state = jnp.zeros_like(hidden_mb[0])

            def tick(carry, t):
                state, outputs = carry
                feed = jax.lax.dynamic_index_in_dim(
                    hidden_mb, jnp.clip(t, 0, num_micro - 1), axis=0,
                    keepdims=False,
                )
                inp = jnp.where(stage == 0, feed, state).astype(cfg.compute_dtype)
                rng_t = None
                if dropout_rng is not None:
                    rng_t = jax.random.fold_in(dropout_rng, 1 + t * num_stages)
                out = _stage_body(cfg, layers_local, inp, rope_table, None,
                                  None, rng_t, deterministic, stage, num_stages)
                out = out.astype(boundary_dtype)
                # last stage banks microbatch t-(pp-1) when in range
                slot = jnp.clip(t - (num_stages - 1), 0, num_micro - 1)
                valid = (stage == num_stages - 1) & (t >= num_stages - 1)
                banked = jax.lax.dynamic_index_in_dim(outputs, slot, 0, False)
                outputs = jax.lax.dynamic_update_index_in_dim(
                    outputs, jnp.where(valid, out, banked), slot, 0
                )
                # rotate stage s -> s+1 (ref: send_forward
                # p2p_communication.py:292; backward of this ppermute is the
                # reverse rotation = send_backward :311)
                state = jax.lax.ppermute(
                    out, STAGE_AXIS,
                    [(i, i + 1) for i in range(num_stages - 1)],
                )
                return (state, outputs), None

            # carries become stage-varying inside the loop; mark the zero
            # initials as varying so the scan carry types are stable
            state = jax.lax.pcast(state, (STAGE_AXIS,), to="varying")
            outputs0 = jax.lax.pcast(
                jnp.zeros_like(hidden_mb), (STAGE_AXIS,), to="varying"
            )
            (_, outputs), _ = jax.lax.scan(
                tick, (state, outputs0), jnp.arange(total)
            )
            # stack over a leading stage axis: each stage contributes its
            # banked buffer (only the last stage's is meaningful); the
            # caller slices [-1], which XLA lowers to one transfer from the
            # last stage (the analogue of the last->first stage broadcast,
            # ref: text_generation/communication.py:111).
            return outputs[None]

        stack_mapped = jax.shard_map(
            stack_shard,
            mesh=mesh,
            in_specs=(P(STAGE_AXIS), P()),
            out_specs=P(STAGE_AXIS),
            axis_names={STAGE_AXIS},
        )
        hidden_out = stack_mapped(
            params["layers"], hidden_micro.astype(jnp.float32)
        )[-1].astype(cfg.compute_dtype)

        # ---- head + loss (stage-replicated) -----------------------------
        def head_micro(hidden, lbls, lmask):
            h = apply_norm(hidden, params["final_norm"], cfg)
            logits = lm_logits(params, cfg, h)
            losses = cross_entropy(logits, lbls)
            if lmask is None:
                return jnp.sum(losses), jnp.float32(losses.size)
            lmask = lmask.astype(jnp.float32)
            return jnp.sum(losses * lmask), jnp.sum(lmask)

        if loss_mask is None:
            sums, denoms = jax.vmap(lambda h, l: head_micro(h, l, None))(
                hidden_out, labels
            )
        else:
            sums, denoms = jax.vmap(head_micro)(hidden_out, labels, loss_mask)
        return jnp.sum(sums) / jnp.maximum(jnp.sum(denoms), 1.0)

    return loss_fn


def make_pipelined_train_step(model, tcfg, pcfg, ctx: ParallelContext):
    """train_step(params, opt_state, batch, lr, wd, rng) for pp > 1
    (ref: train_step + get_forward_backward_func, training.py:391-431)."""
    from megatron_llm_tpu.optimizer.optimizer import optimizer_step

    loss_fn = make_pipelined_loss_fn(model, pcfg, ctx)

    def train_step(params, opt_state, batch, lr, wd, rng=None):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        params, opt_state, stats = optimizer_step(
            params, grads, opt_state, tcfg, lr, weight_decay=wd
        )
        stats["loss"] = loss
        return params, opt_state, stats

    return train_step
