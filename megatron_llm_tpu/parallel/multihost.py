"""Multi-host correctness: per-process data feeding + exit consensus.

Parity targets:
- ref megatron/data: every rank's sampler loads only its own chunk of the
  global batch (data_samplers.py:48-118 strided per-rank sampling). The
  single-controller JAX form: each PROCESS loads only the global-batch
  rows its addressable devices hold along the `data` axis, then
  `jax.make_array_from_process_local_data` assembles the global array —
  no duplicated I/O, no non-addressable transfer errors.
- ref megatron/dist_signal_handler.py:53-57 — SIGTERM flags are
  all-gathered so every rank decides to exit together — and
  training.py:727-739 — the duration check reaches consensus via
  allreduce(MAX). A pod where one host catches the signal (or crosses the
  time limit first) must not desync.
- ref megatron/utils.py:117-135 — ADLR autoresume termination polling;
  the cluster library has no TPU analogue, so the hook here is a sentinel
  file any watchdog can touch.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.parallel.mesh import DATA_AXIS, ParallelContext


# ---------------------------------------------------------------------------
# Per-process batch rows
# ---------------------------------------------------------------------------


def data_axis_span(dp_indices: Sequence[int], rows: int, dp: int
                   ) -> Tuple[int, int]:
    """Pure row-range math: the contiguous [lo, hi) slice of a
    (rows = mbs*dp)-row global batch owned by the data-axis coordinates
    `dp_indices`. Global microbatches are assembled rank-chunks-contiguous
    (data_samplers.py docstring), so coordinate i owns rows
    [i*mbs, (i+1)*mbs)."""
    assert rows % dp == 0, (rows, dp)
    per = rows // dp
    idx = sorted(set(dp_indices))
    assert idx, "process holds no data-axis coordinate"
    assert idx == list(range(idx[0], idx[-1] + 1)), (
        f"process's data-axis coordinates {idx} are not contiguous; "
        "reorder the mesh so each host's devices are contiguous on `data`"
    )
    return idx[0] * per, (idx[-1] + 1) * per


def process_dp_indices(mesh, process_index: Optional[int] = None):
    """Which `data`-axis coordinates have devices on this process."""
    pi = jax.process_index() if process_index is None else process_index
    dev = np.asarray(mesh.devices)
    dp = dev.shape[0]  # data is the outermost mesh axis
    return [i for i in range(dp)
            if any(d.process_index == pi for d in dev[i].flat)]


def process_row_range(ctx: ParallelContext, rows: int) -> Tuple[int, int]:
    """[lo, hi) rows of each global microbatch this process must load."""
    if jax.process_count() == 1:
        return 0, rows
    return data_axis_span(process_dp_indices(ctx.mesh), rows, ctx.dp)


def globalize_batch(batch, ctx: ParallelContext, row_axis: int = 1):
    """Per-process batch leaves with rows (the `data`-sharded dim) at
    `row_axis` -> global jax.Arrays sharded over `data` on that axis.
    Identity on single-process runs (GSPMD places host numpy directly).
    Train batches are (num_micro, rows, ...) [row_axis=1]; eval
    microbatches are (rows, ...) [row_axis=0]."""
    if jax.process_count() == 1:
        return batch

    def glob(x):
        spec = [None] * x.ndim
        spec[row_axis] = DATA_AXIS
        return jax.make_array_from_process_local_data(
            NamedSharding(ctx.mesh, P(*spec)), np.asarray(x)
        )

    return jax.tree.map(glob, batch)


# ---------------------------------------------------------------------------
# Exit consensus (ref: dist_signal_handler.py:53-57, training.py:727-739)
# ---------------------------------------------------------------------------


def all_hosts_any(flag: bool) -> bool:
    """True on EVERY process iff ANY process passed True — the allgather/
    allreduce-MAX consensus the reference uses for signal and duration
    exits. Single-process: the flag itself."""
    if jax.process_count() == 1:
        return bool(flag)
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([1 if flag else 0], np.int32)
    )
    return bool(np.max(flags) > 0)


def host_barrier(tag: str = "barrier") -> None:
    """Every process blocks until ALL processes have reached this call —
    the pod-wide sync around a preemption fast-save (ISSUE 5): the hosts
    agree to save (all_hosts_any on the SIGTERM latch), each contributes
    its shards to the orbax save, then barrier AGAIN so no host exits —
    tearing down its TPU runtime — while a peer is still committing.
    Single-process: no-op. `tag` only aids debugging hung barriers."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


class AutoResume:
    """Sentinel-file termination hook (TPU analogue of ADLR autoresume,
    ref: utils.py:117-135 + training.py:712-725): when `path` exists (a
    cluster watchdog touches it before preemption), every host agrees to
    checkpoint and exit; the file is removed by the first host so the
    relaunched job doesn't immediately re-exit."""

    def __init__(self, path: str, check_interval: int = 50):
        self.path = path
        self.check_interval = max(1, check_interval)

    def termination_requested(self, iteration: int) -> bool:
        if iteration % self.check_interval != 0:
            return False
        local = os.path.exists(self.path)
        hit = all_hosts_any(local)
        # EVERY process that can see the file removes it (hosts may not
        # share a filesystem; first remove wins, the rest tolerate ENOENT)
        # so the relaunched job doesn't immediately re-exit
        if hit and local:
            try:
                os.remove(self.path)
            except OSError:
                pass
        return hit
