"""Device-mesh topology — the TPU-native replacement for the reference's
process-group "mpu" layer (ref: megatron/core/parallel_state.py:51-524).

Where the reference builds NCCL process groups per (tp, pp, dp) coordinate
and offers ~40 rank/size getters, on TPU a single `jax.sharding.Mesh` with
named axes ("data", "stage", "model") carries the whole topology: TP/SP is
sharding over "model", PP over "stage", DP over "data". XLA's GSPMD inserts
the collectives the reference issues by hand.

The rank-order convention matches the reference so multi-host layouts map
the same way: tp is innermost (fastest-varying), then pp, then dp
(ref: parallel_state.py:88-130 builds dp groups with stride tp*pp).
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
STAGE_AXIS = "stage"
CONTEXT_AXIS = "context"
MODEL_AXIS = "model"
AXIS_NAMES = (DATA_AXIS, STAGE_AXIS, CONTEXT_AXIS, MODEL_AXIS)

# jax.shard_map landed as a top-level name only on newer JAX lines; the
# baked-in 0.4.37 still spells it jax.experimental.shard_map.shard_map
# and declares manual axes as `auto` (the complement of the new API's
# `axis_names`). Every call site imports THIS adapter, so the whole
# pp/cp/zero1 shard_map surface works on both lines — this was the
# KNOWN_FAILURES.md "jax.shard_map AttributeError" drift that
# dead-ended the pipeline/context-parallel/pp-inference slow suites and
# the pp>1 MULTICHIP dryrun layouts in this environment.
if hasattr(jax, "shard_map"):
    import inspect as _inspect

    _new_params = _inspect.signature(jax.shard_map).parameters

    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_rep=True, auto=frozenset()):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        # the rep/vma checker kwarg was renamed check_rep -> check_vma
        # on the new surface; pass whichever this jax spells
        if "check_vma" in _new_params:
            kw["check_vma"] = check_rep
        elif "check_rep" in _new_params:
            kw["check_rep"] = check_rep
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_rep=True, auto=frozenset()):
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        # size-1 auto axes are vacuous — treat them as manual. This is
        # load-bearing: ANY non-empty auto set routes this XLA build
        # into its partial-manual partitioner, which is broken
        # (PartitionId UNIMPLEMENTED, or a hard IsManualSubgroup CHECK
        # that ABORTS the process) — so pure-pp/cp/dp meshes must reach
        # it with auto = {} to work at all. Genuinely mixed meshes are
        # rejected HERE with a catchable error: the CHECK-abort variant
        # would otherwise kill the whole test/serve process.
        auto = frozenset(a for a in auto if mesh.shape[a] > 1)
        if auto:
            raise NotImplementedError(
                f"partial-manual shard_map (manual={sorted(set(mesh.axis_names) - auto)}, "
                f"auto={sorted(auto)}) is broken in this jax/XLA build "
                f"(0.4.37 CPU partitioner: PartitionId UNIMPLEMENTED / "
                f"IsManualSubgroup CHECK abort). Use a mesh where the "
                f"non-manual axes are size 1, or a newer jax with "
                f"jax.shard_map (KNOWN_FAILURES.md)")
        # the experimental rep-checker predates the varying-manual type
        # system the new call sites are written for (lax.pcast markers,
        # check_vma) — its inference rejects bodies the new API accepts.
        # Replication checking is a diagnostic, not a semantic: off.
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False,
                              auto=auto)


def pcast(x, axes, to="varying"):
    """jax.lax.pcast where it exists (the new varying-manual type
    system); a no-op marker on older lines, where the experimental
    shard_map (check_rep=False above) needs no varying annotations."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to=to)
    return x


def axis_size(name) -> int:
    """jax.lax.axis_size where it exists; on older lines the canonical
    psum-of-1 idiom, which trace-time folds to a concrete int inside
    shard_map bodies."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return int(jax.lax.psum(1, name))

_CONTEXT: Optional["ParallelContext"] = None

# Thread-local context OVERRIDE (ISSUE 14): `use_mesh` scopes are
# per-thread, so N tp-serving engines' serve threads can each trace
# under their OWN mesh concurrently — a process-global swap would make
# one replica bake another's mesh into its constraints (or force a
# fleet-serializing lock around every dispatch). Reads fall back to
# the installed global (`initialize_parallel`), which trainers and
# tests keep using unchanged.
import threading as _threading

_TLS = _threading.local()


def _effective_context() -> Optional["ParallelContext"]:
    return getattr(_TLS, "ctx", None) or _CONTEXT


def maybe_initialize_distributed() -> int:
    """Multi-host bring-up — the analogue of the reference's
    torch.distributed.init_process_group + NCCL rendezvous
    (ref: initialize.py:180-217).

    On TPU pods the runtime publishes coordinator/task env vars and
    `jax.distributed.initialize()` needs no arguments; after it returns,
    `jax.devices()` spans every host and the (data, stage, model) mesh
    built below automatically lays DCN-crossing axes outermost. No-op on
    single-process runs. Returns the process count.

    MUST run before ANY other jax call (jax.devices()/process_count()
    initialize the local-only backend and make the rendezvous impossible)
    — every entry point calls this first, before args_to_configs touches
    jax.devices(). Real rendezvous failures propagate; only
    double-initialization is tolerated.
    """
    import os

    multiproc_env = any(
        v in os.environ
        for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                  "MEGASCALE_COORDINATOR_ADDRESS")
    )
    # GCE/GKE TPU pods set none of the coordinator vars — jax auto-detects
    # the cluster from TPU metadata. Detect the multi-host pod from the
    # worker-hostnames metadata env var the TPU runtime publishes.
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h]) > 1:
        multiproc_env = True
    if multiproc_env:
        try:
            jax.distributed.initialize()
        except RuntimeError as e:
            if "already" not in str(e):
                raise
    return jax.process_count()


def build_mesh(
    dp: int = 1,
    pp: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    cp: int = 1,
) -> Mesh:
    """Build the (data, stage, context, model) mesh.

    Axis order puts `model` innermost so TP collectives ride the
    fastest ICI links (analogue of the reference keeping TP within a node,
    ref: docs/guide/faq.md policy "TP <= GPUs/node"); `context` sits just
    outside so the ring-attention ppermute hops are next-nearest.
    """
    if devices is None:
        devices = jax.devices()
    n = dp * pp * cp * tp
    if len(devices) < n:
        raise ValueError(
            f"need {n} devices for dp={dp} pp={pp} cp={cp} tp={tp}, "
            f"have {len(devices)}"
        )
    dev_array = np.asarray(devices[:n]).reshape(dp, pp, cp, tp)
    return Mesh(dev_array, AXIS_NAMES)


@dataclass
class ParallelContext:
    """Holds the mesh + parallel flags; the analogue of the reference's
    module-global parallel state (ref: parallel_state.py:20-49)."""

    mesh: Mesh
    sequence_parallel: bool = False

    # -- size getters (ref: parallel_state.py:327-372) --------------------
    @property
    def dp(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def pp(self) -> int:
        return self.mesh.shape[STAGE_AXIS]

    @property
    def cp(self) -> int:
        return self.mesh.shape[CONTEXT_AXIS]

    @property
    def tp(self) -> int:
        return self.mesh.shape[MODEL_AXIS]

    @property
    def world_size(self) -> int:
        return self.dp * self.pp * self.cp * self.tp

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))


def initialize_parallel(
    dp: int = 1, pp: int = 1, tp: int = 1, sequence_parallel: bool = False,
    devices: Optional[Sequence[jax.Device]] = None, cp: int = 1,
) -> ParallelContext:
    """Create and install the global context (ref analogue:
    initialize_model_parallel, parallel_state.py:51)."""
    global _CONTEXT
    mesh = build_mesh(dp, pp, tp, devices, cp=cp)
    _CONTEXT = ParallelContext(mesh=mesh, sequence_parallel=sequence_parallel)
    return _CONTEXT


def get_context() -> Optional[ParallelContext]:
    return _effective_context()


def destroy_parallel() -> None:
    """Ref analogue: destroy_model_parallel (parallel_state.py:497)."""
    global _CONTEXT
    _CONTEXT = None


@contextlib.contextmanager
def use_mesh(ctx: ParallelContext):
    """Temporarily install a context for THIS thread (tests use this
    to swap meshes; tp serving engines scope every dispatch with it).
    Thread-local by design — see _effective_context."""
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = ctx
    try:
        yield ctx
    finally:
        _TLS.ctx = prev


# ---------------------------------------------------------------------------
# Activation sharding constraints
# ---------------------------------------------------------------------------
# Model code calls `shard_activation(x, kind)` at the few load-bearing points;
# when no mesh is installed these are no-ops, so single-device code paths are
# identical. GSPMD propagates everything else.

# The sequence dim is ALWAYS sharded over `context` (a size-1 no-op unless
# context parallelism is on — ring attention handles the one op that mixes
# sequence positions). Under sequence parallelism the norm/dropout regions
# ("hidden_seq") shard seq over `model` TOO: GSPMD then materialises the
# reference's SP all-gather-before-column-parallel / reduce-scatter-after-
# row-parallel pattern (ref: mappings.py:191-246, layers.py:225-296) from
# the transition between "hidden_seq" and the matmul-region specs below,
# and every saved residual/norm activation costs 1/tp the memory.
_ACTIVATION_SPECS = {
    # (batch, seq, hidden) residual stream at matmul regions
    "hidden": P(DATA_AXIS, CONTEXT_AXIS, None),
    # (batch, seq, hidden) at layer boundaries / norm+dropout regions —
    # seq additionally sharded over `model` under sequence parallelism
    "hidden_seq": P(DATA_AXIS, (CONTEXT_AXIS, MODEL_AXIS), None),
    # (batch, seq, heads, head_dim) — heads over model axis (TP attention)
    "heads": P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS, None),
    # (batch, seq, kv_heads, q_per_kv, head_dim) grouped GQA layout
    "groups": P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS, None, None),
    # (batch, seq, ffn) MLP intermediate — ffn over model axis
    "ffn": P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS),
    # (batch, seq, 2, ffn) GLU intermediate, gate/up axis unsharded
    "glu_ffn": P(DATA_AXIS, CONTEXT_AXIS, None, MODEL_AXIS),
    # (batch, seq, vocab) logits — vocab-parallel
    # (ref: layers.py:128-210 VocabParallelEmbedding / parallel_lm_logits)
    "logits": P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS),
}


_MANUAL_DEPTH = 0
_BARRIER_DEPTH = 0


@contextlib.contextmanager
def manual_region(constraint_barriers: bool = False):
    """Mark a shard_map(manual-axes) body: activation constraints are
    skipped inside (this JAX rejects with_sharding_constraint mixing auto
    axes into a manual region; GSPMD propagation from the param shardings
    covers the body instead).

    `constraint_barriers=True` (the explicit ZeRO-1 path,
    optimizer/zero1.py): each skipped constraint site emits a
    `lax.optimization_barrier` instead of nothing. A sharding
    constraint is a fusion boundary in the GSPMD program; without a
    stand-in, the manual program fuses elementwise chains differently
    and bf16 intermediates round differently — measured on the CPU
    backend as a per-layer last-ulp forward divergence. The barrier
    reproduces the replicated program's fusion boundaries, which is
    what makes the zero1-vs-replicated BITWISE contract hold in bf16
    (tests/test_zero1.py)."""
    global _MANUAL_DEPTH, _BARRIER_DEPTH
    _MANUAL_DEPTH += 1
    _BARRIER_DEPTH += 1 if constraint_barriers else 0
    try:
        yield
    finally:
        _MANUAL_DEPTH -= 1
        _BARRIER_DEPTH -= 1 if constraint_barriers else 0


def in_manual_region() -> bool:
    return _MANUAL_DEPTH > 0


@jax.custom_vjp
def _fusion_barrier(x):
    return jax.lax.optimization_barrier(x)


def _fusion_barrier_fwd(x):
    return jax.lax.optimization_barrier(x), None


def _fusion_barrier_bwd(_, ct):
    # with_sharding_constraint transposes to a constraint on the
    # cotangent — the replicated program's BACKWARD has the same fusion
    # boundaries, so the stand-in must too
    return (jax.lax.optimization_barrier(ct),)


_fusion_barrier.defvjp(_fusion_barrier_fwd, _fusion_barrier_bwd)


def shard_activation(x, kind: str):
    ctx = _effective_context()
    if ctx is None or _MANUAL_DEPTH:
        if ctx is not None and _BARRIER_DEPTH:
            return _fusion_barrier(x)
        return x
    spec = _ACTIVATION_SPECS[kind]
    if kind == "hidden_seq" and not ctx.sequence_parallel:
        spec = _ACTIVATION_SPECS["hidden"]
    return jax.lax.with_sharding_constraint(x, NamedSharding(ctx.mesh, spec))
