"""Device-mesh topology — the TPU-native replacement for the reference's
process-group "mpu" layer (ref: megatron/core/parallel_state.py:51-524).

Where the reference builds NCCL process groups per (tp, pp, dp) coordinate
and offers ~40 rank/size getters, on TPU a single `jax.sharding.Mesh` with
named axes ("data", "stage", "model") carries the whole topology: TP/SP is
sharding over "model", PP over "stage", DP over "data". XLA's GSPMD inserts
the collectives the reference issues by hand.

The rank-order convention matches the reference so multi-host layouts map
the same way: tp is innermost (fastest-varying), then pp, then dp
(ref: parallel_state.py:88-130 builds dp groups with stride tp*pp).
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
STAGE_AXIS = "stage"
CONTEXT_AXIS = "context"
MODEL_AXIS = "model"
AXIS_NAMES = (DATA_AXIS, STAGE_AXIS, CONTEXT_AXIS, MODEL_AXIS)

_CONTEXT: Optional["ParallelContext"] = None


def maybe_initialize_distributed() -> int:
    """Multi-host bring-up — the analogue of the reference's
    torch.distributed.init_process_group + NCCL rendezvous
    (ref: initialize.py:180-217).

    On TPU pods the runtime publishes coordinator/task env vars and
    `jax.distributed.initialize()` needs no arguments; after it returns,
    `jax.devices()` spans every host and the (data, stage, model) mesh
    built below automatically lays DCN-crossing axes outermost. No-op on
    single-process runs. Returns the process count.

    MUST run before ANY other jax call (jax.devices()/process_count()
    initialize the local-only backend and make the rendezvous impossible)
    — every entry point calls this first, before args_to_configs touches
    jax.devices(). Real rendezvous failures propagate; only
    double-initialization is tolerated.
    """
    import os

    multiproc_env = any(
        v in os.environ
        for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                  "MEGASCALE_COORDINATOR_ADDRESS")
    )
    # GCE/GKE TPU pods set none of the coordinator vars — jax auto-detects
    # the cluster from TPU metadata. Detect the multi-host pod from the
    # worker-hostnames metadata env var the TPU runtime publishes.
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h]) > 1:
        multiproc_env = True
    if multiproc_env:
        try:
            jax.distributed.initialize()
        except RuntimeError as e:
            if "already" not in str(e):
                raise
    return jax.process_count()


def build_mesh(
    dp: int = 1,
    pp: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    cp: int = 1,
) -> Mesh:
    """Build the (data, stage, context, model) mesh.

    Axis order puts `model` innermost so TP collectives ride the
    fastest ICI links (analogue of the reference keeping TP within a node,
    ref: docs/guide/faq.md policy "TP <= GPUs/node"); `context` sits just
    outside so the ring-attention ppermute hops are next-nearest.
    """
    if devices is None:
        devices = jax.devices()
    n = dp * pp * cp * tp
    if len(devices) < n:
        raise ValueError(
            f"need {n} devices for dp={dp} pp={pp} cp={cp} tp={tp}, "
            f"have {len(devices)}"
        )
    dev_array = np.asarray(devices[:n]).reshape(dp, pp, cp, tp)
    return Mesh(dev_array, AXIS_NAMES)


@dataclass
class ParallelContext:
    """Holds the mesh + parallel flags; the analogue of the reference's
    module-global parallel state (ref: parallel_state.py:20-49)."""

    mesh: Mesh
    sequence_parallel: bool = False

    # -- size getters (ref: parallel_state.py:327-372) --------------------
    @property
    def dp(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def pp(self) -> int:
        return self.mesh.shape[STAGE_AXIS]

    @property
    def cp(self) -> int:
        return self.mesh.shape[CONTEXT_AXIS]

    @property
    def tp(self) -> int:
        return self.mesh.shape[MODEL_AXIS]

    @property
    def world_size(self) -> int:
        return self.dp * self.pp * self.cp * self.tp

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))


def initialize_parallel(
    dp: int = 1, pp: int = 1, tp: int = 1, sequence_parallel: bool = False,
    devices: Optional[Sequence[jax.Device]] = None, cp: int = 1,
) -> ParallelContext:
    """Create and install the global context (ref analogue:
    initialize_model_parallel, parallel_state.py:51)."""
    global _CONTEXT
    mesh = build_mesh(dp, pp, tp, devices, cp=cp)
    _CONTEXT = ParallelContext(mesh=mesh, sequence_parallel=sequence_parallel)
    return _CONTEXT


def get_context() -> Optional[ParallelContext]:
    return _CONTEXT


def destroy_parallel() -> None:
    """Ref analogue: destroy_model_parallel (parallel_state.py:497)."""
    global _CONTEXT
    _CONTEXT = None


@contextlib.contextmanager
def use_mesh(ctx: ParallelContext):
    """Temporarily install a context (tests use this to swap meshes)."""
    global _CONTEXT
    prev = _CONTEXT
    _CONTEXT = ctx
    try:
        yield ctx
    finally:
        _CONTEXT = prev


# ---------------------------------------------------------------------------
# Activation sharding constraints
# ---------------------------------------------------------------------------
# Model code calls `shard_activation(x, kind)` at the few load-bearing points;
# when no mesh is installed these are no-ops, so single-device code paths are
# identical. GSPMD propagates everything else.

# The sequence dim is ALWAYS sharded over `context` (a size-1 no-op unless
# context parallelism is on — ring attention handles the one op that mixes
# sequence positions). Under sequence parallelism the norm/dropout regions
# ("hidden_seq") shard seq over `model` TOO: GSPMD then materialises the
# reference's SP all-gather-before-column-parallel / reduce-scatter-after-
# row-parallel pattern (ref: mappings.py:191-246, layers.py:225-296) from
# the transition between "hidden_seq" and the matmul-region specs below,
# and every saved residual/norm activation costs 1/tp the memory.
_ACTIVATION_SPECS = {
    # (batch, seq, hidden) residual stream at matmul regions
    "hidden": P(DATA_AXIS, CONTEXT_AXIS, None),
    # (batch, seq, hidden) at layer boundaries / norm+dropout regions —
    # seq additionally sharded over `model` under sequence parallelism
    "hidden_seq": P(DATA_AXIS, (CONTEXT_AXIS, MODEL_AXIS), None),
    # (batch, seq, heads, head_dim) — heads over model axis (TP attention)
    "heads": P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS, None),
    # (batch, seq, kv_heads, q_per_kv, head_dim) grouped GQA layout
    "groups": P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS, None, None),
    # (batch, seq, ffn) MLP intermediate — ffn over model axis
    "ffn": P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS),
    # (batch, seq, 2, ffn) GLU intermediate, gate/up axis unsharded
    "glu_ffn": P(DATA_AXIS, CONTEXT_AXIS, None, MODEL_AXIS),
    # (batch, seq, vocab) logits — vocab-parallel
    # (ref: layers.py:128-210 VocabParallelEmbedding / parallel_lm_logits)
    "logits": P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS),
}


_MANUAL_DEPTH = 0


@contextlib.contextmanager
def manual_region():
    """Mark a shard_map(manual-axes) body: activation constraints are
    skipped inside (this JAX rejects with_sharding_constraint mixing auto
    axes into a manual region; GSPMD propagation from the param shardings
    covers the body instead)."""
    global _MANUAL_DEPTH
    _MANUAL_DEPTH += 1
    try:
        yield
    finally:
        _MANUAL_DEPTH -= 1


def in_manual_region() -> bool:
    return _MANUAL_DEPTH > 0


def shard_activation(x, kind: str):
    ctx = _CONTEXT
    if ctx is None or _MANUAL_DEPTH:
        return x
    spec = _ACTIVATION_SPECS[kind]
    if kind == "hidden_seq" and not ctx.sequence_parallel:
        spec = _ACTIVATION_SPECS["hidden"]
    return jax.lax.with_sharding_constraint(x, NamedSharding(ctx.mesh, spec))
