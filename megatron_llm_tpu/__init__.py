"""megatron_llm_tpu — a TPU-native LLM training framework.

A from-scratch JAX/XLA/Pallas re-design with the capabilities of the EPFL
Megatron-LLM fork of Megatron-LM (reference: /root/reference): Llama 1/2,
CodeLlama, Falcon, GPT with GQA/MQA, RoPE (scaling + theta), RMSNorm,
flash attention, SwiGLU, untied embeddings, 3D parallelism
(DP/TP/PP + sequence parallelism) and a ZeRO-1-style distributed optimizer —
expressed the TPU way: one `jax.sharding.Mesh` over (data, stage, model),
GSPMD sharding annotations + XLA collectives instead of NCCL call sites,
`shard_map`+`ppermute` pipelining instead of batched isend/irecv, and Pallas
kernels for the fused hot ops.
"""

__version__ = "0.1.0"

from megatron_llm_tpu.config import (  # noqa: F401
    ModelConfig,
    ParallelConfig,
    TrainConfig,
)
