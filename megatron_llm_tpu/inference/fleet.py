"""Self-driving fleet controller (ISSUE 20): the act half of the
sense->act loop.

PRs 13-15 built the sensors — the perf sentinel (per-round regression
verdicts), the flight recorder (bounded postmortem ring), per-request
device-cost records (`modeled_backlog_seconds`) — and PRs 14/17 built
the fleet (ReplicaRouter poison rotation, disaggregated hand-off,
modeled-backlog admission). But nothing acted on a verdict: a poisoned
or sentinel-regressed replica left rotation and stayed gone, and the
fleet's size was fixed at boot. `FleetController` closes the loop:

- **Replace cycle** (ROADMAP 5a): on a poison verdict (serve loop dead
  / health broken) or a sentinel trip (`serve_perf_regressions` grew
  since the last tick), run condemn -> drain -> stop -> spawn a warmed
  replacement on the freed devices -> rotate back in. The condemned
  replica's flight-record dump rides the router's eviction event, so
  the postmortem artifact and the rotation decision stay correlated.
  In-flight requests on the dead replica are NOT this module's job:
  the router's `recover_requests` proxy resubmits queued and
  un-streamed requests transparently (router.py _RecoverableRequest).

- **Load-adaptive scaling** (ROADMAP 5b, EQuARX's wire-efficiency
  framing applied to fleet capacity): grow/shrink the active set
  against modeled demand — the PR 15 cost records' fleet-wide
  `modeled_backlog_seconds` per active replica vs the scale
  thresholds. Hysteresis (`scale_patience` consecutive identical
  verdicts before acting) keeps it from flapping on a bursty queue.
  Every decision is recorded WITH its inputs, and the verdict function
  is a pure static method — feeding the recorded inputs back through
  `FleetController.decide` replays the same verdicts, which is the
  reproducibility bar tests/test_fleet.py pins.

Everything is off by default: a router only becomes "managed" (and
grows the gated `serve_fleet_replaced`/`serve_scale_events` counters)
when a controller registers on it.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

_logger = logging.getLogger(__name__)

__all__ = ["FleetController"]


class FleetController:
    """Sentinel/poison-driven replace cycles + load-adaptive scaling
    over one ReplicaRouter (module docstring).

    Parameters:
    - `spawn_replica(old) -> replica`: builds (and ideally warms) a
      replacement carrying `old.replica_id`, typically on the devices
      the dead engine freed. Without it the controller degrades to
      condemn-only: a bad replica still leaves rotation permanently,
      it just is not replaced.
    - `check_interval_s`: background-thread tick period.
    - `drain_timeout_s`: how long a condemned replica may finish its
      live slots before the hard stop. The condemn happened first, so
      no NEW work lands on it while it drains.
    - `scale_up_backlog_s` / `scale_down_backlog_s`: per-replica
      modeled-backlog thresholds (seconds). Both None disables
      scaling. Sane settings keep a wide dead band between them
      (up >> down) — the hysteresis streak protects against flapping
      VERDICTS, the dead band against oscillating LOAD.
    - `scale_patience`: consecutive identical non-hold verdicts
      required before acting.
    - `standby`: built-but-idle replicas the scale-up draws from (and
      scale-down returns to). Scale-up without standby capacity holds.
    - `min_replicas` / `max_replicas`: active-set bounds.
    """

    _EVENTS_CAP = 256

    def __init__(self, router, *,
                 spawn_replica: Optional[Callable] = None,
                 check_interval_s: float = 0.5,
                 drain_timeout_s: float = 10.0,
                 scale_up_backlog_s: Optional[float] = None,
                 scale_down_backlog_s: Optional[float] = None,
                 scale_patience: int = 3,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 standby: Optional[List] = None):
        if (scale_up_backlog_s is not None
                and scale_down_backlog_s is not None
                and scale_down_backlog_s >= scale_up_backlog_s):
            raise ValueError(
                f"scale_down_backlog_s ({scale_down_backlog_s}) must "
                f"be < scale_up_backlog_s ({scale_up_backlog_s}) — "
                f"without a dead band the fleet flaps on steady load")
        if scale_patience < 1:
            raise ValueError("scale_patience must be >= 1")
        self.router = router
        self.spawn_replica = spawn_replica
        self.check_interval_s = float(check_interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.scale_up_backlog_s = scale_up_backlog_s
        self.scale_down_backlog_s = scale_down_backlog_s
        self.scale_patience = int(scale_patience)
        self.min_replicas = int(min_replicas)
        self.max_replicas = max_replicas
        self.standby: List = list(standby or [])
        self.events: collections.deque = collections.deque(
            maxlen=self._EVENTS_CAP)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # sentinel-trip detection is a DELTA: the per-replica
        # serve_perf_regressions count at the last tick
        self._sentinel_seen: Dict[int, float] = {}
        self._condemned: set = set()  # condemn-only replicas (no
        # spawn callback): skip them on later ticks instead of
        # re-running the cycle forever
        self._seen_alive: set = set()  # replicas observed healthy at
        # least once: "not alive" only counts as a DEATH after that
        # (a not-yet-started replica is not a poison verdict)
        self._streak_verdict = "hold"
        self._streak = 0
        # registration flips the router into managed mode: its
        # /metrics grows the gated fleet counters, its flight_record
        # the "fleet" decision trail
        router._controller = self
        router._managed = True

    # -- event trail -------------------------------------------------------

    def _note(self, kind: str, **fields) -> None:
        with self._lock:
            self.events.append({"t": time.time(), "kind": kind,
                                **fields})

    def flight_events(self) -> list:
        """The bounded decision/action trail, served under the
        router's flight_record()["fleet"]."""
        with self._lock:
            return [dict(e) for e in self.events]

    # -- replace cycle -----------------------------------------------------

    def _drain_condemned(self, rep) -> bool:
        """Wait (bounded) for the condemned replica's live slots to
        finish — it was condemned FIRST, so the router admits nothing
        new onto it. Returns True when it drained clean, False on
        timeout or death (either way the caller stops it)."""
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            try:
                h = rep.health()
            except Exception:  # noqa: BLE001 — dead is drained
                return False
            if not h.get("alive") or h.get("broken") is not None:
                return False
            if (h.get("queue_depth", 0) == 0
                    and h.get("slots_busy", 0) == 0):
                return True
            time.sleep(0.05)
        return False

    def _replace(self, rep, why: str) -> None:
        """The full replace cycle: condemn -> drain -> stop -> spawn
        warmed replacement -> rotate back in. Degrades to condemn-only
        without a spawn callback."""
        rid = rep.replica_id
        t0 = time.monotonic()
        self.router.condemn(rid, why)
        drained = self._drain_condemned(rep)
        try:
            rep.stop(drain=False)
        except Exception as e:  # noqa: BLE001 — it may already be dead
            _logger.warning("fleet: stopping condemned replica %d "
                            "failed: %r", rid, e)
        dump = None
        fn = getattr(rep, "last_dump_path", None)
        if fn is not None:
            try:
                dump = fn()
            except Exception:  # noqa: BLE001 — advisory attach
                dump = None
        if self.spawn_replica is None:
            self._condemned.add(rid)
            self._note("condemn", replica=rid, why=str(why)[:200],
                       drained=drained, flight_dump=dump)
            _logger.error(
                "fleet: replica %d condemned (%s) with no spawn "
                "callback — fleet is now %d wide", rid, why,
                len(self.router.replicas) - len(self._condemned))
            return
        new = self.spawn_replica(rep)
        wfn = getattr(new, "warmup", None)
        if wfn is not None:
            try:
                wfn()  # compile/first-step cost lands HERE, not on
                # the first request after rotation back in
            except Exception as e:  # noqa: BLE001 — warmup is best-effort
                _logger.warning("fleet: replacement %d warmup failed: "
                                "%r", rid, e)
        new.start()
        self.router.replace_replica(rid, new)
        self.router.note_replaced()
        dt = time.monotonic() - t0
        self._note("replace", replica=rid, why=str(why)[:200],
                   drained=drained, flight_dump=dump,
                   recovery_s=round(dt, 3))
        _logger.warning("fleet: replica %d replaced in %.2fs (%s)",
                        rid, dt, why)

    # -- scaling -----------------------------------------------------------

    @staticmethod
    def decide(backlogs: List[Optional[float]], n_active: int,
               up_threshold_s: Optional[float],
               down_threshold_s: Optional[float]) -> str:
        """Pure scale verdict from one tick's inputs: "up", "down" or
        "hold". Per-replica modeled backlog (fleet sum / active count)
        against the thresholds; holds when ANY replica cannot model
        its backlog (mirrors _order_by_backlog's all-report rule —
        acting on a partial model would be guessing). Static + pure on
        purpose: tests replay recorded decision events through this
        exact function and require the same verdicts."""
        if up_threshold_s is None and down_threshold_s is None:
            return "hold"
        if not backlogs or any(b is None for b in backlogs):
            return "hold"
        per = sum(backlogs) / max(n_active, 1)
        if up_threshold_s is not None and per > up_threshold_s:
            return "up"
        if down_threshold_s is not None and per < down_threshold_s:
            return "down"
        return "hold"

    def _scale_tick(self) -> None:
        if (self.scale_up_backlog_s is None
                and self.scale_down_backlog_s is None):
            return
        active = [r for r in list(self.router.replicas)
                  if r.replica_id not in self._condemned]
        backlogs: List[Optional[float]] = []
        for rep in active:
            fn = getattr(rep, "modeled_backlog_s", None)
            b = None
            if fn is not None:
                try:
                    b = fn()
                except Exception:  # noqa: BLE001 — advisory signal
                    b = None
            backlogs.append(None if b is None else float(b))
        verdict = self.decide(backlogs, len(active),
                              self.scale_up_backlog_s,
                              self.scale_down_backlog_s)
        # hysteresis: only scale_patience consecutive IDENTICAL
        # non-hold verdicts act; anything else resets the streak
        if verdict == self._streak_verdict and verdict != "hold":
            self._streak += 1
        else:
            self._streak_verdict = verdict
            self._streak = 1 if verdict != "hold" else 0
        acted = None
        if self._streak >= self.scale_patience:
            if verdict == "up":
                acted = self._scale_up()
            elif verdict == "down":
                acted = self._scale_down(active, backlogs)
            self._streak_verdict, self._streak = "hold", 0
        # every decision — acted or not — is an event carrying the
        # exact decide() inputs: the reproducibility contract
        self._note("scale_decision", verdict=verdict,
                   backlogs=[None if b is None else round(b, 4)
                             for b in backlogs],
                   n_active=len(active),
                   up_threshold_s=self.scale_up_backlog_s,
                   down_threshold_s=self.scale_down_backlog_s,
                   streak=self._streak, acted=acted)

    def _scale_up(self) -> Optional[str]:
        n = len(self.router.replicas)
        cap = self.max_replicas
        if cap is not None and n >= cap:
            return "held:max_replicas"
        if not self.standby:
            return "held:no_standby"
        rep = self.standby.pop(0)
        wfn = getattr(rep, "warmup", None)
        if wfn is not None:
            try:
                wfn()
            except Exception as e:  # noqa: BLE001
                _logger.warning("fleet: standby %d warmup failed: %r",
                                rep.replica_id, e)
        rep.start()
        self.router.add_replica(rep)
        self.router.note_scale_event()
        _logger.warning("fleet: scaled UP to %d replicas (+%d)",
                        len(self.router.replicas), rep.replica_id)
        return f"added:{rep.replica_id}"

    def _scale_down(self, active, backlogs) -> Optional[str]:
        if len(active) <= self.min_replicas:
            return "held:min_replicas"
        # shed the least-backlogged replica: fewest in-flight tokens
        # to drain, and the modeled numbers are already in hand
        pairs = sorted(zip(active, backlogs),
                       key=lambda p: (p[1] if p[1] is not None else 0.0,
                                      p[0].replica_id))
        victim = pairs[0][0]
        rid = victim.replica_id
        try:
            rep = self.router.remove_replica(rid)
        except (KeyError, ValueError) as e:
            return f"held:{e}"
        try:
            rep.drain()
            rep.stop(drain=False)
        except Exception as e:  # noqa: BLE001 — shed anyway
            _logger.warning("fleet: draining removed replica %d "
                            "failed: %r", rid, e)
        self.standby.append(rep)
        self.router.note_scale_event()
        _logger.warning("fleet: scaled DOWN to %d replicas (-%d)",
                        len(self.router.replicas), rid)
        return f"removed:{rid}"

    # -- the tick ----------------------------------------------------------

    def tick(self) -> None:
        """One sense->act pass: poison scan, sentinel scan, scale
        decision. Public (and deterministic given replica state) so
        tier-1 tests drive the controller without its thread."""
        for rep in list(self.router.replicas):
            rid = rep.replica_id
            if rid in self._condemned:
                continue
            # poison verdict: the serve loop died or health is broken
            try:
                h = rep.health()
            except Exception as e:  # noqa: BLE001 — dead host
                self._replace(rep, f"health probe failed: {e!r}")
                continue
            broken = h.get("broken")
            alive = bool(h.get("alive"))
            if alive and broken is None:
                self._seen_alive.add(rid)
            if broken is not None or (not alive
                                      and rid in self._seen_alive):
                self._replace(rep, broken or "serve loop dead")
                continue
            # sentinel trip: the regression counter grew since our
            # last look (the sentinel already logged + dumped; ours is
            # the remediation verdict)
            try:
                trips = float(rep.counters().get(
                    "serve_perf_regressions", 0))
            except Exception:  # noqa: BLE001 — advisory signal
                continue
            seen = self._sentinel_seen.get(rid, 0.0)
            self._sentinel_seen[rid] = trips
            if trips > seen:
                self._replace(
                    rep, f"perf sentinel tripped "
                         f"({trips:.0f} regressions, was {seen:.0f})")
        self._scale_tick()

    # -- lifecycle ---------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the controller must
                # outlive one bad tick; the next tick retries
                _logger.exception("fleet: tick failed")

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-controller")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        self._thread = None
