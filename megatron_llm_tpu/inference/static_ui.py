"""Minimal generation web UI served at GET / by the REST server.

Parity target: ref megatron/static/index.html — a prompt textarea that
PUTs to /api and appends the completion. Kept as a Python string so the
server stays a single-module stdlib deployment.
"""

INDEX_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8"/>
<title>megatron_llm_tpu</title>
<style>
.wrapper { max-width: 75%; margin: auto; font-family: sans-serif; }
h1 { margin: 3rem 0 1rem 0; font-size: 1.5rem; }
textarea { width: 100%; min-height: 300px; border-radius: 8px;
           border: 1px solid #ddd; padding: .5rem; font-size: 1rem; }
button { margin-top: .5rem; padding: .5rem 1.5rem; border-radius: 8px;
         border: 1px solid #888; background: #f5f5f5; cursor: pointer; }
#status { margin-left: 1rem; color: #666; }
label { margin-right: 1rem; }
</style>
</head>
<body>
<div class="wrapper">
<h1>megatron_llm_tpu text generation</h1>
<textarea id="prompt" placeholder="Enter a prompt..."></textarea><br/>
<label>tokens <input id="n" type="number" value="64" min="1" style="width:5rem"/></label>
<label>top_k <input id="topk" type="number" value="1" min="0" style="width:5rem"/></label>
<label>temperature <input id="temp" type="number" value="1.0" step="0.1" style="width:5rem"/></label>
<br/>
<button onclick="gen()">Generate</button><span id="status"></span>
<script>
async function gen() {
  const t = document.getElementById('prompt');
  const status = document.getElementById('status');
  status.textContent = 'generating...';
  try {
    const resp = await fetch('/api', {
      method: 'PUT',
      headers: {'Content-Type': 'application/json'},
      body: JSON.stringify({
        prompts: [t.value],
        tokens_to_generate: parseInt(document.getElementById('n').value),
        top_k: parseInt(document.getElementById('topk').value),
        temperature: parseFloat(document.getElementById('temp').value),
      }),
    });
    const data = await resp.json();
    if (resp.ok) { t.value = data.text[0]; status.textContent = ''; }
    else { status.textContent = 'error: ' + JSON.stringify(data); }
  } catch (e) { status.textContent = 'error: ' + e; }
}
</script>
</div>
</body>
</html>
"""
