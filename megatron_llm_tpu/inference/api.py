"""Public inference API.

Parity target: ref megatron/text_generation/api.py —
`generate_and_post_process` (:19), `generate` (:70) and
`beam_search_and_post_process` (:147). The reference's sampling-parameter
broadcast from rank 0 (:100-127) disappears: one controller drives the
mesh.
"""

from __future__ import annotations

import logging
from typing import List, Optional

import jax
import numpy as np

_logger = logging.getLogger(__name__)

from megatron_llm_tpu.analysis.contracts import (
    CompileContract,
    record_variant,
    register_contract,
    release_variant,
)
from megatron_llm_tpu.inference.generation import (
    beam_search,
    bucket_prefill_len,
    generate_tokens,
    score_tokens,
)

register_contract(CompileContract(
    name="api.pp_decode",
    max_variants=8,  # == the LRU cap below; eviction releases, so the
    # live variant count IS the executable cache occupancy
    collectives=None,  # lowering needs a pp mesh + stage-sharded model;
    # test_pp_inference exercises the ring — variants/markers audited
    notes="pp>1 pipelined decode, LRU-bounded per (model, mesh, "
          "statics); every eviction warns (recompile footgun)"))
register_contract(CompileContract(
    name="api.pp_score",
    max_variants=4,  # == the LRU cap below; eviction releases, so the
    # live variant count IS the executable cache occupancy
    collectives=None,
    notes="pp>1 pipelined scorer, LRU-bounded per (model, mesh); "
          "keyed on the model OBJECT"))
from megatron_llm_tpu.inference.tokenization import (
    detokenize_generations,
    tokenize_prompts,
)


# (model, mesh) -> jitted pipelined scorer. Keyed on the model OBJECT
# (strong ref, object-identity hash) — keying on id() alone could alias a
# recycled address after the model is garbage-collected and silently serve
# a scorer traced for the old config (ADVICE r4). The params cache
# likewise holds strong refs and compares identity.
_PP_SCORE_CACHE: dict = {}
_PP_PARAMS_CACHE: dict = {}  # {"model": .., "mesh": .., "src": .., "out": ..}
_PP_DECODE_CACHE: dict = {}  # (model, mesh, statics) -> jitted decode

# Above this model size the pp>1 decode path keeps params stage-sharded
# and pipelines tokens through the stage ring (parallel/pipeline.py
# make_pipelined_decode_fn) instead of paying reshard's pp x per-device
# param memory (VERDICT r4 #4; ref analogue: the batch*seqlen dispatch of
# text_generation/forward_step.py:61-73).
#
# REQUEST CONTRACT (ADVICE r5): this threshold is part of the serving
# contract, not an internal tuning knob — deployments pin it via
# MEGATRON_TPU_PP_RESHARD_LIMIT_BYTES. Only GREEDY requests
# (top_k_sampling == 1) ride the stage ring, where they are exact-match
# with the single-mesh engine (tests/test_pp_inference.py); sampled
# requests never do, because the ring's per-position RNG fold differs
# from generate_tokens' — routing them by model size would make the same
# random_seed yield deployment-dependent samples. At or under the limit
# sampled/beam requests reshard stage-replicated; ABOVE it they fail
# loudly with the alternatives (see generate_and_post_process /
# beam_search_and_post_process) instead of silently paying pp x the
# per-device param memory. Documented in docs/GUIDE.md ("Serving on a
# pp>1 mesh").
import os as _os

PP_DECODE_RESHARD_LIMIT_BYTES = int(_os.environ.get(
    "MEGATRON_TPU_PP_RESHARD_LIMIT_BYTES", 2 << 30
))


def _params_nbytes(params) -> int:
    return sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(params))


def _pp_decode_fn(model, ctx, statics):
    key = (model, ctx.mesh, statics)
    if key in _PP_DECODE_CACHE:
        # LRU requeue: pop + reinsert moves the hit to the back of the
        # dict's insertion order, so hot shapes survive churn
        fn = _PP_DECODE_CACHE.pop(key)
        _PP_DECODE_CACHE[key] = fn
        return fn
    # bound the executable cache: shape statics vary per request (max_len
    # AND prefill_len are bucketed by the caller, so the key space is
    # small but unbounded across traffic). Eviction is LEAST-RECENTLY-
    # USED — the requeue above, then drop the front — capped at 8, and
    # every eviction WARNS: the evicted shape's next request silently
    # pays a full pipeline recompile, the #1 serving-latency footgun.
    while len(_PP_DECODE_CACHE) >= 8:
        old_key = next(iter(_PP_DECODE_CACHE))
        _PP_DECODE_CACHE.pop(old_key)
        # the contract budget counts LIVE executables: eviction un-counts
        release_variant("api.pp_decode", old_key)
        _logger.warning(
            "pp decode executable cache full (8): evicting LRU entry "
            "with statics %s; the next request at that shape recompiles "
            "the pipelined decode", old_key[2],
        )
    from megatron_llm_tpu.config import ParallelConfig
    from megatron_llm_tpu.parallel.pipeline import (
        make_pipelined_decode_fn,
    )

    (prefill_len, max_len, greedy, top_k, top_p, temperature,
     vocab_size, termination_id, use_eod_early,
     return_log_probs) = statics
    pcfg = ParallelConfig(pipeline_parallel_size=ctx.pp,
                          tensor_parallel_size=ctx.tp,
                          context_parallel_size=ctx.cp)
    # graft-contract: api.pp_decode
    fn = jax.jit(make_pipelined_decode_fn(
        model, pcfg, ctx, prefill_len=prefill_len, max_len=max_len,
        greedy=greedy, top_k=top_k, top_p=top_p,
        temperature=temperature, vocab_size=vocab_size,
        termination_id=termination_id,
        use_eod_for_early_termination=use_eod_early,
        return_log_probs=return_log_probs,
    ))
    # record AFTER the build: a builder exception must never leave a
    # phantom live variant the LRU eviction (which only releases keys it
    # pops from the cache) could never un-count
    record_variant("api.pp_decode", key)
    _PP_DECODE_CACHE[key] = fn
    return fn


def _pp_score_fn(model, ctx):
    key = (model, ctx.mesh)
    if key in _PP_SCORE_CACHE:
        # LRU requeue, same policy as _pp_decode_fn
        fn = _PP_SCORE_CACHE.pop(key)
        _PP_SCORE_CACHE[key] = fn
        return fn
    # bound the cache at the contract budget: (model, mesh) keys are
    # unbounded across checkpoint reloads that build fresh model
    # objects — without eviction the 5th distinct model would turn
    # cache growth into an unrecoverable ContractViolation
    while len(_PP_SCORE_CACHE) >= 4:
        old_key = next(iter(_PP_SCORE_CACHE))
        _PP_SCORE_CACHE.pop(old_key)
        release_variant("api.pp_score", old_key)
        _logger.warning(
            "pp score executable cache full (4): evicting LRU entry; "
            "the next score at that (model, mesh) recompiles the "
            "pipelined scorer",
        )
    from megatron_llm_tpu.config import ParallelConfig
    from megatron_llm_tpu.parallel.pipeline import (
        make_pipelined_score_fn,
    )

    pcfg = ParallelConfig(pipeline_parallel_size=ctx.pp,
                          tensor_parallel_size=ctx.tp,
                          context_parallel_size=ctx.cp)
    # graft-contract: api.pp_score
    fn = jax.jit(make_pipelined_score_fn(model, pcfg, ctx))
    # record AFTER the build, as in _pp_decode_fn
    record_variant("api.pp_score", key)
    _PP_SCORE_CACHE[key] = fn
    return fn


def _pp_serving_params(model, ctx, params):
    import weakref

    leaves = jax.tree.leaves(params)
    c = _PP_PARAMS_CACHE
    refs = c.get("src_refs")
    if (c.get("model") is model and c.get("mesh") == ctx.mesh
            and refs is not None and len(refs) == len(leaves)
            and all(r() is l for r, l in zip(refs, leaves))):
        return c["out"]
    from megatron_llm_tpu.parallel.pipeline import (
        reshard_params_for_inference,
    )

    out = reshard_params_for_inference(params, ctx, model.cfg)
    # weakrefs to EVERY leaf: identity of the whole tree, without pinning
    # the stale source in memory after a checkpoint reload (jax.Array
    # leaves are weakref-able; any dead/changed ref misses the cache —
    # partial param updates that reuse some leaf objects still miss)
    try:
        src_refs = tuple(weakref.ref(l) for l in leaves)
    except TypeError:
        src_refs = None
    c.clear()  # one serving tree at a time
    c.update(model=model, mesh=ctx.mesh, src_refs=src_refs, out=out)
    return out


def generate_and_post_process(
    model,
    params,
    tokenizer,
    prompts: List[str],
    tokens_to_generate: int = 0,
    return_output_log_probs: bool = False,
    top_k_sampling: int = 0,
    top_p_sampling: float = 0.0,
    top_p_decay: float = 0.0,
    top_p_bound: float = 0.0,
    temperature: float = 1.0,
    add_BOS: bool = False,
    use_eod_token_for_early_termination: bool = True,
    stop_on_eol: bool = False,  # accepted for API parity; eol ids are
    stop_on_double_eol: bool = False,  # tokenizer-specific (ref TODO :243)
    prevent_newline_after_colon: bool = False,
    random_seed: int = -1,
):
    """Returns (prompts_plus_generations, segments, output_log_probs,
    tokens) — the reference's return contract (api.py:19-67).

    Request contract on a pp>1 mesh (ADVICE r5; docs/GUIDE.md
    "Serving on a pp>1 mesh"):

    - GREEDY requests (top_k_sampling == 1) may decode through the
      pipelined stage ring, which is exact-match with the single-mesh
      path — the route is an internal placement choice with no output
      effect.
    - NON-GREEDY sampling is ROUTE-DEPENDENT: the stage ring's
      per-position RNG fold differs from generate_tokens', so the same
      `random_seed` would yield different (both individually correct)
      samples depending on which path served it. Sampled requests
      therefore never ride the ring — below the reshard limit they
      decode stage-replicated (seed-stable, matching the single-mesh
      path); above it they fail loudly rather than silently switch
      RNG semantics or pay pp x the per-device param memory.
    - `PP_DECODE_RESHARD_LIMIT_BYTES` (env
      MEGATRON_TPU_PP_RESHARD_LIMIT_BYTES) is therefore PART OF THE
      REQUEST CONTRACT, not a tuning knob: it decides which sampled
      requests a deployment accepts at all. Pin it per deployment;
      changing it changes which requests succeed, never what any
      successful request returns."""
    tokens, lengths = tokenize_prompts(
        tokenizer, prompts, tokens_to_generate, add_BOS
    )

    # pp>1 mesh: score through the pipelined forward (stage-sharded params
    # stay put); decode reshards params stage-replicated — both memoized
    # per (model, mesh) / params so repeated requests neither re-trace the
    # pipelined scan nor re-transfer the weights
    # (ref analogue: text_generation/forward_step.py:61-73 pipelined
    # inference vs the last-stage decode loop)
    from megatron_llm_tpu.parallel.mesh import get_context

    ctx = get_context()
    pp_pipelined = False
    if ctx is not None and ctx.pp > 1:
        if tokens_to_generate == 0:
            import jax.numpy as jnp

            s = tokens.shape[1]
            pad = (-s) % ctx.cp  # context-sharded seq must divide by cp
            scored = (jnp.pad(tokens, ((0, 0), (0, pad)))
                      if pad else tokens)
            lp = np.asarray(
                _pp_score_fn(model, ctx)(params, scored[None])[0]
            )[:, : s - 1]
            texts, segments = detokenize_generations(
                tokenizer, tokens, lengths, return_segments=True
            )
            return texts, segments, lp, tokens
        # big models stay stage-sharded and decode through the ring;
        # small ones pay reshard once and use the plain engine. GREEDY
        # ONLY (ADVICE r5): the ring's RNG fold differs from
        # generate_tokens', so sampled outputs would depend on which
        # route the model size selected; greedy is route-invariant
        # (exact-match tested). The pipelined path also lacks the
        # colon-newline and top-p-decay knobs. Above the limit,
        # ring-ineligible requests FAIL LOUDLY (same contract as beam):
        # silently resharding would blow the operator-pinned per-device
        # memory budget by pp x.
        nbytes = _params_nbytes(params)
        ring_eligible = (ctx.cp == 1
                         and top_k_sampling == 1
                         and not prevent_newline_after_colon
                         and top_p_decay == 0.0)
        if ring_eligible and nbytes > PP_DECODE_RESHARD_LIMIT_BYTES:
            pp_pipelined = True
        else:
            if nbytes > PP_DECODE_RESHARD_LIMIT_BYTES:
                raise ValueError(
                    "pp>1 generate: only plain greedy requests on a "
                    "cp=1 mesh (top_k == 1, no "
                    "prevent_newline_after_colon / top_p_decay) ride "
                    f"the stage ring, and this model ({nbytes} bytes) "
                    "exceeds PP_DECODE_RESHARD_LIMIT_BYTES "
                    f"({PP_DECODE_RESHARD_LIMIT_BYTES}) so it cannot "
                    "reshard stage-replicated without pp x the "
                    "per-device param memory. Use greedy decoding, "
                    "raise MEGATRON_TPU_PP_RESHARD_LIMIT_BYTES to "
                    "accept the reshard, or serve these requests from "
                    "a pp=1 mesh (docs/GUIDE.md, 'Serving on a pp>1 "
                    "mesh')"
                )
            params = _pp_serving_params(model, ctx, params)

    if tokens_to_generate == 0:
        # score-only mode (ref: api.py:48-56 -> score_and_return...)
        lp = np.asarray(score_tokens(model, params, tokens))
        texts, segments = detokenize_generations(
            tokenizer, tokens, lengths, return_segments=True
        )
        return texts, segments, lp, tokens

    pnac_ids = None
    if prevent_newline_after_colon:
        colon = tokenizer.tokenize(":")
        newline = tokenizer.tokenize("\n")
        if colon and newline:
            pnac_ids = (colon[0], newline[0])

    rng = None
    if top_k_sampling != 1:
        # random_seed < 0 means "unseeded": the reference leaves torch's
        # global PRNG alone so repeated requests differ (api.py:100-109);
        # mirror that with a fresh OS-entropy seed per call.
        if random_seed >= 0:
            seed = random_seed
        else:
            import os as _os

            seed = int.from_bytes(_os.urandom(4), "little")
        rng = jax.random.key(seed)

    # prefill the longest common BUCKETED prefix; the rest of each prompt
    # is teacher-forced by the decode loop. `prefill_len` is a jit static
    # of generate_tokens (and of the pp decode statics below), so it must
    # come from a bounded bucket set: multiples of 64, powers of two
    # below 64 (bucket_prefill_len). Passing the raw min length minted
    # one executable per distinct short-prompt length
    # (tests/test_server.py::test_prefill_bucketing_bounds_executables).
    min_len = int(np.min(lengths))
    prefill_len = bucket_prefill_len(min_len)

    if pp_pipelined:
        b, max_len = tokens.shape
        nm = ctx.pp
        toks_in = np.asarray(tokens)
        lens_in = np.asarray(lengths)
        pad_rows = (-b) % nm
        if pad_rows:  # batch must split evenly into pp round-robin groups
            toks_in = np.concatenate(
                [toks_in, np.repeat(toks_in[-1:], pad_rows, 0)])
            lens_in = np.concatenate(
                [lens_in, np.repeat(lens_in[-1:], pad_rows, 0)])
        # bucket max_len to 64 so the compiled-executable cache stays
        # small across varying request lengths (extra columns are decoded
        # then trimmed by out_lengths below)
        max_len_b = -(-max_len // 64) * 64
        if max_len_b > max_len:
            toks_in = np.concatenate(
                [toks_in,
                 np.zeros((toks_in.shape[0], max_len_b - max_len),
                          toks_in.dtype)], axis=1)
        greedy = top_k_sampling == 1 or rng is None
        statics = (
            prefill_len, max_len_b, greedy, top_k_sampling, top_p_sampling,
            temperature, tokenizer.vocab_size, tokenizer.eod,
            use_eod_token_for_early_termination, return_output_log_probs,
        )
        dec = _pp_decode_fn(model, ctx, statics)
        import jax.numpy as jnp

        out_toks, out_lens, out_lps = dec(
            params, jnp.asarray(toks_in), jnp.asarray(lens_in), rng
        )
        out_tokens = np.asarray(out_toks)[:b, :max_len]
        out_lengths = np.minimum(np.asarray(out_lens)[:b],
                                 lengths + tokens_to_generate)
        texts, segments = detokenize_generations(
            tokenizer, out_tokens, out_lengths, return_segments=True
        )
        lp = (np.asarray(out_lps)[:b, : max_len - 1]
              if return_output_log_probs else None)
        return texts, segments, lp, out_tokens

    out = generate_tokens(
        model,
        params,
        tokens,
        lengths,
        prefill_len=prefill_len,
        rng=rng,
        top_k=top_k_sampling,
        top_p=top_p_sampling,
        top_p_decay=top_p_decay,
        top_p_bound=top_p_bound,
        temperature=temperature,
        vocab_size=tokenizer.vocab_size,
        termination_id=tokenizer.eod,
        return_log_probs=return_output_log_probs,
        use_eod_for_early_termination=use_eod_token_for_early_termination,
        prevent_newline_after_colon_ids=pnac_ids,
    )
    out_tokens = np.asarray(out.tokens)
    out_lengths = np.minimum(np.asarray(out.lengths),
                             lengths + tokens_to_generate)
    texts, segments = detokenize_generations(
        tokenizer, out_tokens, out_lengths, return_segments=True
    )
    lp = np.asarray(out.log_probs) if out.log_probs is not None else None
    return texts, segments, lp, out_tokens


def beam_search_and_post_process(
    model,
    params,
    tokenizer,
    prompts: List[str],
    tokens_to_generate: int = 0,
    beam_size: int = 0,
    add_BOS: bool = False,
    stop_token: Optional[int] = None,
    num_return_gen: int = 1,
    length_penalty: float = 1.0,
    prevent_newline_after_colon: bool = False,
):
    """ref: beam_search_and_post_process (api.py:147-201).

    pp>1 mesh (VERDICT r5 weak #7): beam search has no stage-ring path
    (the beam reorder gathers/permutes the WHOLE KV cache along the batch
    axis every step — on stage-sharded caches that is a per-step
    cross-stage reshuffle the ring schedule cannot hide). Models at or
    under PP_DECODE_RESHARD_LIMIT_BYTES take the same reshard dispatch as
    `generate_and_post_process`; above it the request FAILS LOUDLY with
    the supported alternatives rather than silently allocating pp x the
    per-device param memory.
    """
    assert len(prompts) == 1, "beam search: batch size must be 1"
    from megatron_llm_tpu.parallel.mesh import get_context

    ctx = get_context()
    if ctx is not None and ctx.pp > 1:
        nbytes = _params_nbytes(params)
        if nbytes > PP_DECODE_RESHARD_LIMIT_BYTES:
            raise ValueError(
                "beam search on a pp>1 mesh requires stage-replicated "
                f"params, but the model ({nbytes} bytes) exceeds "
                "PP_DECODE_RESHARD_LIMIT_BYTES "
                f"({PP_DECODE_RESHARD_LIMIT_BYTES}): no stage-ring beam "
                "path exists (the per-step beam reorder would reshuffle "
                "stage-sharded KV caches across the ring). Raise "
                "MEGATRON_TPU_PP_RESHARD_LIMIT_BYTES to accept the "
                "pp x param-memory reshard, serve beam requests from a "
                "pp=1 mesh, or use sampling/greedy `generate` which does "
                "pipeline (docs/GUIDE.md, 'Serving on a pp>1 mesh')"
            )
        params = _pp_serving_params(model, ctx, params)
    tokens, lengths = tokenize_prompts(
        tokenizer, prompts, tokens_to_generate, add_BOS
    )
    stop = stop_token if stop_token is not None else tokenizer.eod
    out_tokens, scores = beam_search(
        model,
        params,
        tokens[:1],
        prompt_length=int(lengths[0]),
        beam_size=beam_size,
        stop_token=stop,
        num_return_gen=num_return_gen,
        length_penalty=length_penalty,
        vocab_size=tokenizer.vocab_size,
        max_new_tokens=tokens_to_generate,
    )
    out_tokens = np.asarray(out_tokens)
    out_lengths = np.full((out_tokens.shape[0],), out_tokens.shape[1],
                          np.int32)
    # trim trailing stop padding per row
    for i in range(out_tokens.shape[0]):
        row = out_tokens[i]
        n = len(row)
        while n > int(lengths[0]) and row[n - 1] == stop:
            n -= 1
        out_lengths[i] = n
    texts, segments = detokenize_generations(
        tokenizer, out_tokens, out_lengths, return_segments=True
    )
    return texts, segments, np.asarray(scores), out_tokens
