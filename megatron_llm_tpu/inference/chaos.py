"""Deterministic fault injection for the serving fleet (ISSUE 20).

The self-driving fleet (inference/fleet.py) is only as trustworthy as
the failures it has been proven against, and real failures — a replica
poisoned mid-round, a flapping health endpoint, a corrupted KV hand-off
— are miserable to reproduce on demand. `ChaosPolicy` is the test
substrate: a seeded, fully deterministic fault injector that plugs into
the EXISTING failure paths instead of simulating parallel ones. A kill
raises inside the engine's scheduler round, so the serve loop dies
through its real poison path (flight-ring dump, `_fail_all`, `_broken`)
— exactly what a device fault produces. A stall sleeps inside the
round's timed window, so the perf sentinel trips on the same
per-token-advance series it watches in production. A dropped probe
makes the router's health probe fail the way a dead host does. A
corrupted hand-off payload trips the receiver's `_check_payload`
geometry gate.

Everything is off by default and bitwise-invisible when off: replicas
carry `chaos=None`, the engine's `_fault_hook` stays None (one
attribute check per round), and no counter or schema changes shape.

Spec strings (the serving tool's `--chaos` knob, ChaosPolicy.parse):

    kill=RID            kill replica RID on its next scheduler round
    kill=RID@N          ... once RID has accepted N submits
    stall=RID:MSxK      sleep MS milliseconds in each of RID's next K
                        scheduler rounds (sentinel-trip fuel)
    submit_latency_ms=F sleep F ms on every replica submit
    probe_latency_ms=F  sleep F ms on every HTTPReplica health probe
    probe_drop=P        drop each health probe with probability P
    probe_drop=P@RID    ... only replica RID's probes
    corrupt_handoff     corrupt every exported KV hand-off payload
                        (wrong page_size -> receiver degrades to a
                        local prefill, never a poisoned splice)
    seed=N              the injector's RNG seed (default 0)

Example: `--chaos "kill=1@8,probe_drop=0.3,seed=7"`.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

__all__ = ["ChaosFault", "ChaosPolicy"]


class ChaosFault(RuntimeError):
    """The injected kill. Raised from inside a scheduler round so it
    propagates through the serve loop's real poison path; the string
    rides `engine._broken` and every failed waiter's error, which is
    how tests (and the resubmit path's death-marker match) identify an
    injected death."""


class ChaosPolicy:
    """Seeded deterministic fault injector (module docstring). One
    policy instance serves a whole fleet: faults target replicas by id,
    and every injection appends a structured entry to `events` (bounded)
    so a chaos run's fault schedule is auditable after the fact."""

    _EVENTS_CAP = 1024

    def __init__(self, *, seed: int = 0,
                 kill_replica: Optional[int] = None,
                 kill_after_submits: int = 0,
                 stall_replica: Optional[int] = None,
                 stall_ms: float = 0.0,
                 stall_rounds: int = 0,
                 submit_latency_ms: float = 0.0,
                 probe_latency_ms: float = 0.0,
                 probe_drop_rate: float = 0.0,
                 probe_drop_replica: Optional[int] = None):
        if not 0.0 <= probe_drop_rate <= 1.0:
            raise ValueError(
                f"probe_drop_rate must be in [0, 1], got {probe_drop_rate}")
        if kill_after_submits < 0 or stall_rounds < 0:
            raise ValueError("kill_after_submits / stall_rounds must be "
                             ">= 0")
        self.seed = int(seed)
        self.kill_replica = kill_replica
        self.kill_after_submits = int(kill_after_submits)
        self.stall_replica = stall_replica
        self.stall_ms = float(stall_ms)
        self.stall_rounds = int(stall_rounds)
        self.submit_latency_ms = float(submit_latency_ms)
        self.probe_latency_ms = float(probe_latency_ms)
        self.probe_drop_rate = float(probe_drop_rate)
        self.probe_drop_replica = probe_drop_replica
        # one seeded stream per fault kind: each stream's draw sequence
        # depends only on how often ITS fault was consulted, so e.g.
        # probe-drop decisions replay identically whether or not a kill
        # also fired that run
        self._probe_rng = random.Random(self.seed ^ 0x9E3779B9)
        self._lock = threading.Lock()
        self._submits: Dict[int, int] = {}
        self._stalls_left = self.stall_rounds
        self.killed: List[int] = []
        self.events: List[dict] = []

    # -- parse (the --chaos knob) ------------------------------------------

    @classmethod
    def parse(cls, spec: str) -> "ChaosPolicy":
        """Build a policy from the serving tool's comma-separated spec
        string (module docstring grammar). Unknown keys fail loudly —
        a typo'd chaos knob silently injecting nothing would make a
        green convergence run meaningless."""
        kw: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            key, _, val = part.partition("=")
            key = key.strip()
            val = val.strip()
            if key == "kill":
                rid, _, after = val.partition("@")
                kw["kill_replica"] = int(rid)
                if after:
                    kw["kill_after_submits"] = int(after)
            elif key == "stall":
                rid, _, rest = val.partition(":")
                ms, _, rounds = rest.partition("x")
                kw["stall_replica"] = int(rid)
                kw["stall_ms"] = float(ms)
                kw["stall_rounds"] = int(rounds) if rounds else 1
            elif key == "submit_latency_ms":
                kw["submit_latency_ms"] = float(val)
            elif key == "probe_latency_ms":
                kw["probe_latency_ms"] = float(val)
            elif key == "probe_drop":
                rate, _, rid = val.partition("@")
                kw["probe_drop_rate"] = float(rate)
                if rid:
                    kw["probe_drop_replica"] = int(rid)
            elif key == "corrupt_handoff":
                if val not in ("", "1", "true", "True"):
                    raise ValueError(
                        f"chaos: corrupt_handoff takes no value, got "
                        f"{val!r}")
                kw["corrupt_handoff"] = True
            elif key == "seed":
                kw["seed"] = int(val)
            else:
                raise ValueError(f"chaos: unknown fault {key!r} in "
                                 f"{spec!r}")
        corrupt = kw.pop("corrupt_handoff", False)
        policy = cls(**kw)
        policy.corrupt_handoff = corrupt
        return policy

    corrupt_handoff = False

    # -- bookkeeping -------------------------------------------------------

    def _note(self, kind: str, **fields) -> None:
        with self._lock:
            if len(self.events) < self._EVENTS_CAP:
                self.events.append({"t": time.time(), "kind": kind,
                                    **fields})

    # -- replica-side hooks ------------------------------------------------

    def on_submit(self, replica_id: Optional[int]) -> None:
        """Called by a replica as it accepts a submit: injects submit
        latency and advances the kill-arming submit count."""
        with self._lock:
            self._submits[replica_id] = self._submits.get(replica_id,
                                                          0) + 1
        if self.submit_latency_ms > 0:
            self._note("submit_latency", replica=replica_id,
                       ms=self.submit_latency_ms)
            time.sleep(self.submit_latency_ms / 1e3)

    def kill_armed(self, replica_id: Optional[int]) -> bool:
        """Whether the configured kill should fire for this replica
        now: the target matches, it has not fired yet, and the replica
        has accepted at least `kill_after_submits` submits."""
        if self.kill_replica is None or replica_id != self.kill_replica:
            return False
        with self._lock:
            if replica_id in self.killed:
                return False
            return (self._submits.get(replica_id, 0)
                    >= self.kill_after_submits)

    def engine_hook(self, replica_id: Optional[int]):
        """The per-round fault hook installed on a replica's engine
        (`engine._fault_hook`): stalls sleep INSIDE the round's timed
        window (the sentinel measures them honestly), kills raise
        ChaosFault into the serve loop's poison path."""

        def hook(_engine) -> None:
            if (self.stall_replica == replica_id
                    and self.stall_ms > 0):
                fire = False
                with self._lock:
                    if self._stalls_left > 0:
                        self._stalls_left -= 1
                        fire = True
                if fire:
                    self._note("stall", replica=replica_id,
                               ms=self.stall_ms)
                    time.sleep(self.stall_ms / 1e3)
            if self.kill_armed(replica_id):
                with self._lock:
                    self.killed.append(replica_id)
                self._note("kill", replica=replica_id)
                raise ChaosFault(
                    f"chaos: injected kill of replica {replica_id}")

        return hook

    def on_probe(self, replica_id: Optional[int]) -> bool:
        """Called by HTTPReplica before each health probe: injects
        probe latency; returns True when this probe should be DROPPED
        (the replica then reports the same synthetic-unhealthy snapshot
        a dead host produces). Drop decisions come from the policy's
        own seeded stream — same seed, same probe sequence, same
        drops."""
        if self.probe_latency_ms > 0:
            self._note("probe_latency", replica=replica_id,
                       ms=self.probe_latency_ms)
            time.sleep(self.probe_latency_ms / 1e3)
        if self.probe_drop_rate <= 0.0:
            return False
        if (self.probe_drop_replica is not None
                and replica_id != self.probe_drop_replica):
            return False
        with self._lock:
            drop = self._probe_rng.random() < self.probe_drop_rate
        if drop:
            self._note("probe_drop", replica=replica_id)
        return drop

    def on_export(self, replica_id: Optional[int], payload):
        """Called by a replica on each KV hand-off export: with
        `corrupt_handoff` armed, returns a SHALLOW-corrupted copy —
        page_size off by one — that the receiver's `_check_payload`
        geometry gate rejects with ValueError. The corruption is
        metadata-only on a copy: the donor's real payload (and pools)
        are untouched, and the receiver refuses the splice instead of
        decoding garbage — which is the degrade-not-fail property the
        chaos matrix proves."""
        if not self.corrupt_handoff or payload is None:
            return payload
        bad = dict(payload)
        bad["page_size"] = int(bad.get("page_size", 0)) + 1
        self._note("corrupt_handoff", replica=replica_id)
        return bad
