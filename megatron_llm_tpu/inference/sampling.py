"""Token sampling: greedy, top-k, top-p, temperature.

Parity target: ref megatron/text_generation/sampling.py:14-93 — including
the top-p filter's one-position shift (keep the first token whose
cumulative probability crosses top_p, ref :30-38) and the padded-vocab
clamp. All jnp, shapes static, usable inside jitted decode loops.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e10  # matches the reference's masked_fill value semantics


def modify_logits_for_top_k(logits: jnp.ndarray, top_k: int) -> jnp.ndarray:
    """Keep only the top-k logits (ref :14-18). `top_k` is static."""
    kth = jax.lax.top_k(logits, top_k)[0][..., -1, None]
    return jnp.where(logits < kth, NEG_INF, logits)


def modify_logits_for_top_p(logits: jnp.ndarray, top_p) -> jnp.ndarray:
    """Nucleus filtering (ref :22-41), including the shift-by-1 that keeps
    the first token crossing the cumulative-probability boundary."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_indices = jnp.argsort(logits, axis=-1)[..., ::-1]
    cum_probs = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
    filt = cum_probs > top_p
    filt = jnp.concatenate(
        [jnp.zeros_like(filt[..., :1]), filt[..., :-1]], axis=-1
    )  # ref :30-36: shift right, always keep rank 0
    # scatter back to original vocab order via the inverse permutation
    inv = jnp.argsort(sorted_indices, axis=-1)
    filt = jnp.take_along_axis(filt, inv, axis=-1)
    return jnp.where(filt, NEG_INF, logits)


def sample(
    logits: jnp.ndarray,  # (b, v) float
    rng: Optional[jax.Array] = None,
    top_k: int = 0,
    top_p: float = 0.0,
    temperature: float = 1.0,
    vocab_size: Optional[int] = None,
) -> jnp.ndarray:
    """Sample one token per row (ref: sample :45-93). top_k=1 (or rng None)
    is greedy argmax; top_k and top_p are mutually exclusive. `top_k`,
    `top_p`, `temperature`, `vocab_size` are static."""
    assert logits.ndim == 2
    logits = logits.astype(jnp.float32)
    if vocab_size is not None and vocab_size < logits.shape[-1]:
        # never sample padded-vocab ids (ref :49-52 vocab_size guard)
        pad = jnp.arange(logits.shape[-1]) >= vocab_size
        logits = jnp.where(pad[None, :], NEG_INF, logits)

    if top_k == 1 or rng is None:
        assert top_p == 0.0 or rng is None, \
            "cannot set both greedy and top-p samplings"
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    if temperature != 1.0:
        logits = logits / temperature
    if top_k > 1:
        assert top_p == 0.0, "cannot set both top-k and top-p samplings"
        assert top_k <= logits.shape[-1]
        logits = modify_logits_for_top_k(logits, top_k)
    elif top_p > 0.0:
        assert 0.0 < top_p <= 1.0
        logits = modify_logits_for_top_p(logits, top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
