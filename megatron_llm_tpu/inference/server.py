"""REST text-generation server, reference API contract.

Parity target: ref megatron/text_generation_server.py — `MegatronGenerate`
(PUT /api, :17-233, including every request-validation message) and
`MegatronServer` (:234-241). The reference needs flask_restful plus a
broadcast to wake the non-rank-0 GPU cohort (:22-29); the JAX build is
single-controller, so a stdlib ThreadingHTTPServer replaces both (flask
isn't in the image; the HTTP surface is identical).

Dispatch (ISSUE 3): with a `DecodeEngine` attached, generate requests
are ENQUEUED — each prompt becomes one engine request carrying its own
tokens_to_generate / sampling knobs, admitted mid-flight into free
slots, so concurrent PUTs batch together instead of serializing. A full
queue returns 503 + Retry-After. Score-only, beam and the knobs the
engine does not speak (prevent_newline_after_colon, top_p_decay) take
the whole-batch path under a NON-BLOCKING device lock: a second
concurrent request gets 503 + Retry-After instead of stacking device
work behind a blocked thread (two unlocked concurrent PUTs used to race
on the same device; stacking them hid the overload from the client).
`MegatronServer.stop()` drains the engine before returning.

GET /metrics (engine-attached servers) returns the live
`DecodeEngine.counters()` dict — slot occupancy, queue depth, page
accounting, tok/s, and the ISSUE-4 latency gauges (serve_ttft_p50_ms /
serve_ttft_p95_ms / serve_decode_p95_ms) — as JSON. Under content
negotiation (ISSUE 13: `Accept: text/plain` / `application/
openmetrics-text`, or `?format=prometheus`) the same endpoint serves
the Prometheus text exposition instead — every numeric counter as a
gauge plus REAL histograms (TTFT / decode-round ms / queue wait,
telemetry/prometheus.py); the default JSON schema is byte-compatible
with the pre-telemetry surface (tests/test_telemetry.py pins it).

Observability surface (ISSUE 13, engine-attached servers only):
- GET /flight_record — the engine's flight-recorder snapshot (last-N
  structured rounds + counters), the same artifact a dying engine
  auto-dumps;
- POST /profile {"rounds": N, "trace_dir": ...} — arm a jax.profiler
  device capture of the next N engine rounds (one at a time; 409 when
  busy; an unsupported runtime records a loud no-op);
- GET /memory — per-device allocator stats (jax memory_stats), the
  device-memory snapshot endpoint.

GET /health (ISSUE 5) is the load-balancer probe: 200 while the serving
path can take traffic, 503 once the engine's serve loop died poisoned
(`DecodeEngine._broken`) or its thread stopped, with the engine's
liveness snapshot (alive / broken / queue_depth / slots_busy) as the
body. Engineless servers always answer 200.

SSE token streaming (ISSUE 6): a PUT with `{"stream": true}` (exactly
one prompt, engine path only) answers `text/event-stream` — one `data:`
event per generated token, written the moment the engine books it, a
final `{"done": ...}` event, then connection close (EOF = end of
stream). Validation failures answer plain JSON before any bytes stream.
A mid-stream client disconnect cancels the engine request: the slot
retires and its pages return to the pool (prefix-cache refcounts
intact). `stream_enabled=False` (`--no_stream`) turns the surface off.

Replica fleets (ISSUE 14): `engine=` also accepts a `ReplicaRouter`
(inference/router.py) — it duck-types the engine surface this module
uses (submit/cancel/counters/health/prometheus_metrics/flight_record/
start/stop + the admission limits), so the same handler serves N
prefix-affinity-routed engine replicas: /metrics aggregates additive
counters and merges the replicas' latency histograms (remote replicas'
rebuilt from their scraped Prometheus exposition — ISSUE 15), /health
answers for the fleet (alive while any replica takes traffic), and the
SSE `id:` field carries "replica-rid" so streams stay attributable.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from megatron_llm_tpu.inference.api import (
    beam_search_and_post_process,
    generate_and_post_process,
)

_logger = logging.getLogger(__name__)

def _wants_prometheus(accept: str, query: str) -> bool:
    """/metrics content negotiation (ISSUE 13): serve the Prometheus
    text exposition only when the client PREFERS it — an explicit
    `?format=prometheus`, or an Accept header whose first matching
    media type (left-to-right, the client's preference order) is a
    text/openmetrics type rather than JSON. A bare substring test
    would flip clients that merely LIST text/plain as a fallback
    (axios' default `application/json, text/plain, */*`) off the
    byte-compatible legacy JSON they were built against. q-values are
    ignored; list order carries the preference, which every real
    scraper/client default satisfies."""
    if "format=prometheus" in query:
        return True
    for part in accept.split(","):
        mtype = part.split(";", 1)[0].strip().lower()
        if mtype in ("application/json", "*/*", "application/*"):
            return False
        if mtype in ("text/plain", "application/openmetrics-text",
                     "text/*"):
            return True
    return False


GENERATE_NUM = 0
BEAM_NUM = 1
LOCK = threading.Lock()
BUSY_MSG = "server is busy processing another request"
QUEUE_FULL_MSG = "generation queue is full"


class MegatronGenerate:
    """Request validation + dispatch (ref: MegatronGenerate :17-233)."""

    def __init__(self, model, params, tokenizer, engine=None,
                 request_deadline_s=None, stream_enabled=True):
        self.model = model
        self.params = params
        self.tokenizer = tokenizer
        self.engine = engine
        # server-wide wall-clock budget applied to every engine request
        # (DecodeEngine deadline semantics: expiry fails the waiter and
        # reclaims the slot's pages); None = no deadline
        self.request_deadline_s = request_deadline_s
        # SSE token streaming ({"stream": true} PUTs) — gate for
        # deployments that front this server with a buffering proxy
        self.stream_enabled = stream_enabled
        # incremental-detokenization window bound: pending tokens are
        # re-decoded per event (SentencePiece spacing / split multi-byte
        # correctness), and the window resets past this many tokens so
        # long generations stay O(window) per token, not O(n)
        self.stream_flush_tokens = 64

    def _validate(self, raw: dict):
        """The ONE request-validation surface (shared by the buffered
        and streaming paths): returns an (error_payload, http_status)
        tuple on failure — messages mirror the reference byte for byte
        where applicable — or a dict of parsed fields."""
        if "prompts" not in raw:
            return "prompts argument required", 400
        if "max_len" in raw:
            return "max_len is no longer used.  Replace with tokens_to_generate", 400
        if "sentences" in raw:
            return "sentences is no longer used.  Replace with prompts", 400
        prompts = raw["prompts"]
        if not isinstance(prompts, list):
            return "prompts is not a list of strings", 400
        if len(prompts) == 0:
            return "prompts is empty", 400
        if len(prompts) > 128:
            return "Maximum number of prompts is 128", 400

        tokens_to_generate = raw.get("tokens_to_generate", 64)
        if not isinstance(tokens_to_generate, int):
            return "tokens_to_generate must be an integer greater than 0", 400
        if tokens_to_generate < 0:
            return ("tokens_to_generate must be an integer greater than or "
                    "equal to 0"), 400

        logprobs = raw.get("logprobs", False)
        if not isinstance(logprobs, bool):
            return "logprobs must be a boolean value", 400
        if tokens_to_generate == 0 and not logprobs:
            return "tokens_to_generate=0 implies logprobs should be True", 400

        temperature = raw.get("temperature", 1.0)
        if not isinstance(temperature, (int, float)) or not (
            0.0 < temperature <= 100.0
        ):
            return ("temperature must be a positive number less than or "
                    "equal to 100.0"), 400

        top_k = raw.get("top_k", 0)
        if not isinstance(top_k, int) or not (0 <= top_k <= 1000):
            return "top_k must be an integer equal to or greater than 0 and less than or equal to 1000", 400

        top_p = raw.get("top_p", 0.0)
        if not isinstance(top_p, (int, float)) or not (0.0 <= top_p <= 1.0):
            return "top_p must be less than or equal to 1 and greater than or equal to 0", 400
        if top_p > 0.0 and top_k > 0:
            return "cannot set both top-k and top-p samplings.", 400

        top_p_decay = raw.get("top_p_decay", 0.0)
        top_p_bound = raw.get("top_p_bound", 0.0)
        add_BOS = raw.get("add_BOS", False)
        if not isinstance(add_BOS, bool):
            return "add_BOS must be a boolean value", 400
        if any(len(p) == 0 for p in prompts) and not add_BOS:
            return "Empty prompts require add_BOS=true", 400

        stop_on_double_eol = raw.get("stop_on_double_eol", False)
        stop_on_eol = raw.get("stop_on_eol", False)
        prevent_newline_after_colon = raw.get(
            "prevent_newline_after_colon", False
        )
        random_seed = raw.get("random_seed", -1)
        no_log = raw.get("no_log", False)
        beam_width = raw.get("beam_width", None)
        stop_token = raw.get("stop_token", None)
        length_penalty = raw.get("length_penalty", 1.0)

        if beam_width is not None:
            if not isinstance(beam_width, int) or beam_width < 1:
                return "beam_width must be integer > 0", 400
            if len(prompts) > 1:
                return "When doing beam_search, batch size must be 1", 400

        return {
            "prompts": prompts,
            "tokens_to_generate": tokens_to_generate,
            "logprobs": logprobs,
            "temperature": temperature,
            "top_k": top_k,
            "top_p": top_p,
            "top_p_decay": top_p_decay,
            "top_p_bound": top_p_bound,
            "add_BOS": add_BOS,
            "stop_on_eol": stop_on_eol,
            "stop_on_double_eol": stop_on_double_eol,
            "prevent_newline_after_colon": prevent_newline_after_colon,
            "random_seed": random_seed,
            "no_log": no_log,
            "beam_width": beam_width,
            "stop_token": stop_token,
            "length_penalty": length_penalty,
        }

    def put(self, raw: dict):
        """Returns (payload, http_status); validation messages mirror the
        reference byte for byte where applicable."""
        v = self._validate(raw)
        if not isinstance(v, dict):
            return v
        prompts = v["prompts"]
        tokens_to_generate = v["tokens_to_generate"]
        logprobs = v["logprobs"]
        temperature = v["temperature"]
        top_k = v["top_k"]
        top_p = v["top_p"]
        top_p_decay = v["top_p_decay"]
        top_p_bound = v["top_p_bound"]
        add_BOS = v["add_BOS"]
        stop_on_eol = v["stop_on_eol"]
        stop_on_double_eol = v["stop_on_double_eol"]
        prevent_newline_after_colon = v["prevent_newline_after_colon"]
        random_seed = v["random_seed"]
        beam_width = v["beam_width"]
        stop_token = v["stop_token"]
        length_penalty = v["length_penalty"]

        # continuous-batching dispatch: everything the engine speaks goes
        # through its queue (per-request knobs, slot-level admission); the
        # engine-ineligible residue (score-only, beam, pnac/top_p_decay)
        # keeps the whole-batch path below
        if (self.engine is not None and beam_width is None
                and tokens_to_generate > 0
                and not prevent_newline_after_colon
                and top_p_decay == 0.0):
            resp = self._put_engine(
                prompts, tokens_to_generate, logprobs, top_k, top_p,
                temperature, add_BOS, random_seed,
            )
            if resp is not None:
                return resp
            # None: the request exceeds the engine's max_context/pool —
            # a capability the whole-batch path still has; fall through

        # one whole-batch generation at a time (ref :186) — but NON-
        # blocking: a concurrent request is overload, and the honest
        # answer is 503 + Retry-After, not device work stacking up
        # behind a blocked handler thread
        if not LOCK.acquire(blocking=False):
            return {"message": BUSY_MSG}, 503
        try:
            try:
                if beam_width is not None:
                    texts, segments, scores, _ = beam_search_and_post_process(
                        self.model, self.params, self.tokenizer, prompts,
                        tokens_to_generate=tokens_to_generate,
                        beam_size=beam_width,
                        add_BOS=add_BOS,
                        stop_token=stop_token,
                        num_return_gen=beam_width,
                        length_penalty=length_penalty,
                        prevent_newline_after_colon=prevent_newline_after_colon,
                    )
                    return {
                        "text": texts,
                        "segments": segments,
                        "scores": scores.tolist(),
                    }, 200
                texts, segments, lp, _ = generate_and_post_process(
                    self.model, self.params, self.tokenizer, prompts,
                    tokens_to_generate=tokens_to_generate,
                    return_output_log_probs=logprobs,
                    top_k_sampling=top_k,
                    top_p_sampling=top_p,
                    top_p_decay=top_p_decay,
                    top_p_bound=top_p_bound,
                    temperature=temperature,
                    add_BOS=add_BOS,
                    stop_on_eol=stop_on_eol,
                    stop_on_double_eol=stop_on_double_eol,
                    prevent_newline_after_colon=prevent_newline_after_colon,
                    random_seed=random_seed,
                )
                return {
                    "text": texts,
                    "segments": segments,
                    "logprobs": lp.tolist() if lp is not None else None,
                }, 200
            except Exception as e:  # ref returns jsonified error (:230)
                return {"message": repr(e)}, 500
        finally:
            LOCK.release()

    def _prompt_ids(self, prompt, add_BOS):
        """ONE definition of prompt-id construction for the engine
        paths (buffered + streaming)."""
        ids = self.tokenizer.tokenize(prompt)
        if add_BOS:
            ids = [self.tokenizer.bos] + ids
        return ids

    @staticmethod
    def _request_seed(random_seed, index=0):
        """ONE definition of per-request seed derivation: a
        non-negative random_seed is deterministic (decorrelated per
        batch row by index — engine RNG is per request, not per batch
        position), otherwise fresh OS entropy per request."""
        if random_seed >= 0:
            return random_seed + index
        import os as _os

        return int.from_bytes(_os.urandom(4), "little")

    def _put_engine(self, prompts, tokens_to_generate, logprobs, top_k,
                    top_p, temperature, add_BOS, random_seed):
        """Queue each prompt as one engine request and wait for all of
        them; the response shape matches the whole-batch path (ragged
        logprobs: one list per prompt). Returns None — caller falls back
        to the whole-batch path — when any prompt exceeds the engine's
        max_context or page pool (those limits don't exist there)."""
        import numpy as np

        from megatron_llm_tpu.inference.engine import QueueFull
        from megatron_llm_tpu.inference.tokenization import (
            detokenize_generations,
        )

        tok = self.tokenizer
        prompt_ids = [self._prompt_ids(p, add_BOS) for p in prompts]
        eng = self.engine
        pool_tokens = (eng.num_pages - 1) * eng.page_size
        if any(len(ids) + tokens_to_generate
               > min(eng.max_context, pool_tokens)
               for ids in prompt_ids):
            return None
        reqs = []
        try:
            for i, ids in enumerate(prompt_ids):
                seed = self._request_seed(random_seed, i)
                try:
                    reqs.append(self.engine.submit(
                        ids, tokens_to_generate,
                        top_k=top_k, top_p=top_p, temperature=temperature,
                        seed=seed, return_log_probs=logprobs,
                        use_eod_for_early_termination=True,
                        deadline_s=self.request_deadline_s,
                    ))
                except QueueFull:
                    # admitted prefixes of THIS PUT still complete; the
                    # client retries the whole request after Retry-After
                    return {"message": QUEUE_FULL_MSG}, 503
            rows, lps = [], []
            for r in reqs:
                try:
                    toks, lp = r.result(timeout=600.0)
                except TimeoutError as e:
                    # per-request deadline expiry (engine deadline_s) is
                    # overload shed, not an engine fault: 504 +
                    # Retry-After so clients and monitoring can tell it
                    # from a real 5xx crash. rid in the log: the
                    # correlation key into trace spans + flight record
                    _logger.warning("engine request rid=%d timed out "
                                    "(deadline shed)", r.rid)
                    return {"message": repr(e)}, 504
                rows.append(toks)
                lps.append(lp)
            max_len = max(len(t) for t in rows)
            buf = np.full((len(rows), max_len), tok.eod, np.int32)
            for i, t in enumerate(rows):
                buf[i, : len(t)] = t
            lengths = np.asarray([len(t) for t in rows], np.int32)
            texts, segments = detokenize_generations(
                tok, buf, lengths, return_segments=True)
            return {
                "text": texts,
                "segments": segments,
                "logprobs": ([list(map(float, l)) for l in lps]
                             if logprobs else None),
            }, 200
        except Exception as e:  # same jsonified-error contract (:230)
            # log the request IDs this PUT carried (ISSUE 13): a 500 in
            # a client's logs greps to the exact engine rounds by rid
            _logger.error("engine generate PUT failed (rids=%s): %r",
                          [r.rid for r in reqs], e)
            return {"message": repr(e)}, 500

    def put_stream(self, raw: dict, start_response, write_event):
        """SSE token streaming for `{"stream": true}` PUTs (ISSUE 6):
        exactly one prompt rides the engine queue with a per-request
        token queue (`DecodeEngine.submit(stream=True)`), and every
        generated token is written as one `data:` event the moment the
        scheduler books it — chunked-prefill TTFT reaches the client
        instead of dying in a buffered response.

        Contract: returns an (error_payload, status) tuple while
        nothing has been sent (the handler answers plain JSON); once
        eligible it calls `start_response()` (the handler sends the 200
        + `text/event-stream` headers), then `write_event(dict)` per
        token, a final `{"done": ...}` event, and returns None. A
        failing write (client disconnected mid-stream) CANCELS the
        engine request — the slot retires and its pages return to the
        pool with refcounts intact — and re-raises so the handler
        drops the connection."""
        if not self.stream_enabled:
            return {"message": "token streaming is disabled "
                               "(--no_stream)"}, 400
        if self.engine is None:
            return {"message": "token streaming requires the "
                               "continuous-batching engine "
                               "(--serving_slots > 0)"}, 400
        v = self._validate(raw)
        if not isinstance(v, dict):
            return v
        if len(v["prompts"]) != 1:
            return {"message": "streaming serves exactly one prompt "
                               "per request"}, 400
        if v["tokens_to_generate"] < 1:
            return {"message": "streaming requires tokens_to_generate "
                               ">= 1"}, 400
        if (v["beam_width"] is not None
                or v["prevent_newline_after_colon"]
                or v["top_p_decay"] != 0.0):
            return {"message": "streaming supports only engine-path "
                               "requests (no beam_width / "
                               "prevent_newline_after_colon / "
                               "top_p_decay)"}, 400
        if v["logprobs"]:
            # reject instead of silently dropping: the buffered engine
            # path DOES return logprobs, and a stream that quietly
            # omits them would be a lying API surface
            return {"message": "streaming does not return logprobs; "
                               "drop logprobs or use the buffered "
                               "path"}, 400

        import queue as _queue

        from megatron_llm_tpu.inference.engine import QueueFull

        # everything before start_response() must answer plain JSON —
        # after it, the 200 is on the wire and errors can only arrive
        # as a final event
        try:
            tok = self.tokenizer
            ids = self._prompt_ids(v["prompts"][0], v["add_BOS"])
            seed = self._request_seed(v["random_seed"])
            req = self.engine.submit(
                ids, v["tokens_to_generate"], top_k=v["top_k"],
                top_p=v["top_p"], temperature=v["temperature"],
                seed=seed, use_eod_for_early_termination=True,
                deadline_s=self.request_deadline_s, stream=True,
            )
        except QueueFull:
            return {"message": QUEUE_FULL_MSG}, 503
        except ValueError as e:
            # past the engine's max_context/pool: the whole-batch
            # fallback cannot stream, so the honest answer is the limit
            return {"message": repr(e)}, 400
        except Exception as e:  # same jsonified-error contract as put()
            return {"message": repr(e)}, 500

        # the SSE `id:` correlation key (ISSUE 13/14): rid alone on a
        # standalone engine (the pinned legacy surface); "replica-rid"
        # once the serving engine is a tagged replica behind the
        # router, so N replicas' ids stay distinguishable client-side.
        # Resolved PER EVENT, not at submit: a two-stage hand-off
        # proxy (ISSUE 17) has no engine identity until the decode
        # replica attaches — and its first token only flows after that
        def sse_id():
            return (req.rid if getattr(req, "replica_id", None) is None
                    else f"{req.replica_id}-{req.rid}")
        out_ids = []
        # INCREMENTAL detokenization over a bounded tail window: decode
        # the pending tokens and emit the suffix delta — a per-token
        # detokenize would drop SentencePiece word-boundary spaces and
        # mojibake multi-byte chars split across tokens, while decoding
        # the FULL running sequence per token would be quadratic in
        # generation length. A trailing U+FFFD is an unfinished byte
        # sequence: hold it back until its continuation arrives. At the
        # flush threshold the window resets keeping ONE overlap token,
        # so the next window never starts at a bare piece boundary (the
        # final done event's full-sequence text is authoritative
        # regardless).
        pending = []
        win_emitted = ""
        flush_at = self.stream_flush_tokens
        try:
            # from here on the request is live: ANY failure — including
            # the client disconnecting before the headers flush — must
            # cancel it, or the slot decodes every remaining token for
            # a dead connection
            start_response()
            while True:
                t = req.stream_q.get(timeout=600.0)
                if t is None:
                    break
                out_ids.append(int(t))
                pending.append(int(t))
                cur = tok.detokenize(pending)
                stable = cur
                while stable.endswith("�"):
                    stable = stable[:-1]
                delta = ""
                if stable.startswith(win_emitted):
                    delta = stable[len(win_emitted):]
                    win_emitted = stable
                if len(pending) >= flush_at:
                    if stable == cur:
                        pending = pending[-1:]
                        win_emitted = tok.detokenize(pending)
                    elif len(pending) >= 4 * flush_at:
                        # degenerate undecodable tail (e.g. byte-
                        # fallback pieces that never complete): force
                        # the reset anyway — bounded per-token cost
                        # beats re-decoding the whole generation, and
                        # the final event's text is authoritative
                        pending = pending[-flush_at:]
                        win_emitted = tok.detokenize(pending)
                        while win_emitted.endswith("�"):
                            win_emitted = win_emitted[:-1]
                write_event({"token": int(t), "text": delta},
                            rid=sse_id())
        except _queue.Empty:
            # stalled engine: reclaim the slot and tell the client
            # before closing — an EOF with no done event looks like a
            # transport bug, not a server decision
            _logger.error("stream rid=%d stalled waiting for the "
                          "engine; cancelling", req.rid)
            self.engine.cancel(req)
            try:
                write_event({"done": True, "rid": req.rid,
                             "error": "timed out waiting for the "
                                      "engine; request cancelled"},
                            rid=sse_id())
            except Exception:
                pass
            return None
        except Exception:
            # the client went away mid-stream: reclaim the slot + pages
            # NOW instead of decoding for a closed socket. rid in the
            # log line: the greppable key into the engine's trace spans
            # and flight record (ISSUE 13)
            _logger.info("stream rid=%d aborted mid-flight; cancelling",
                         req.rid)
            self.engine.cancel(req)
            raise
        final = {"done": True, "rid": req.rid, "tokens": list(out_ids)}
        if req.error is not None:
            final = {"done": True, "rid": req.rid, "error": req.error}
        else:
            final["text"] = tok.detokenize(ids + out_ids)
        write_event(final, rid=sse_id())
        return None


class _Handler(BaseHTTPRequestHandler):
    generator: Optional[MegatronGenerate] = None

    def do_GET(self):
        # the reference serves its static generation UI at /
        # (megatron/static/index.html via flask static routing)
        if self.path in ("/", "/index.html"):
            from megatron_llm_tpu.inference.static_ui import INDEX_HTML

            data = INDEX_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if self.path.rstrip("/") == "/health":
            # liveness/readiness probe (ISSUE 5): 200 while the serving
            # path can take traffic, 503 once the engine's serve loop
            # died poisoned (DecodeEngine._broken) or its thread is gone
            # — a load balancer drains the replica instead of feeding
            # requests into hung waiters. Engineless (whole-batch-only)
            # servers are always 200: every PUT runs inline.
            eng = self.generator.engine
            if eng is None:
                self._respond({"status": "ok", "engine": None}, 200)
                return
            h = eng.health()
            healthy = h["broken"] is None and h["alive"]
            self._respond(
                {"status": "ok" if healthy else "unhealthy", "engine": h},
                200 if healthy else 503)
            return
        path, _, query = self.path.partition("?")
        if path.rstrip("/") == "/metrics":
            # live engine counters (DecodeEngine.counters — occupancy,
            # queue depth, pages, tok/s, the latency gauges
            # serve_ttft_p50/p95_ms + serve_decode_p95_ms, and the
            # ISSUE-9 capacity gauges serve_kv_dtype /
            # serve_kv_pool_bytes / serve_kv_bytes_per_token) as JSON; the
            # same dict the timers-gauge export carries, so dashboards
            # and curl read one schema. 404 when no engine is attached
            # (whole-batch-only server has no per-request gauges).
            # ISSUE 13: a Prometheus scraper negotiates the text
            # exposition (with real histograms) via Accept or
            # ?format=prometheus; the JSON default stays byte-compatible.
            if self.generator.engine is None:
                self.send_error(404)
                return
            if _wants_prometheus(self.headers.get("Accept", ""), query):
                from megatron_llm_tpu.telemetry import (
                    PROMETHEUS_CONTENT_TYPE,
                )

                data = self.generator.engine.prometheus_metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            self._respond(self.generator.engine.counters(), 200)
            return
        if path.rstrip("/") == "/flight_record":
            # on-demand flight-recorder snapshot (ISSUE 13): the same
            # last-N-rounds record + counters a dying engine dumps —
            # the live postmortem surface
            if self.generator.engine is None:
                self.send_error(404)
                return
            self._respond(self.generator.engine.flight_record(), 200)
            return
        if path.rstrip("/") == "/memory":
            # device-memory snapshot (ISSUE 13): per-device allocator
            # stats; devices without memory_stats report {} rather than
            # failing the probe
            import jax

            devs = []
            for d in jax.local_devices():
                try:
                    stats = d.memory_stats() or {}
                except Exception:  # noqa: BLE001 — stats are optional
                    stats = {}
                devs.append({"device": str(d),
                             "platform": d.platform, **stats})
            self._respond({"devices": devs}, 200)
            return
        self.send_error(404)

    def do_POST(self):
        # POST /profile (ISSUE 13): arm a jax.profiler capture of the
        # next N engine rounds. One capture at a time (409 on overlap);
        # unsupported runtimes record a loud no-op in the flight ring
        # rather than failing the serve loop.
        if self.path.partition("?")[0].rstrip("/") != "/profile":
            self.send_error(404)
            return
        if self.generator.engine is None:
            self.send_error(404)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = json.loads(self.rfile.read(length) or b"{}")
        except (json.JSONDecodeError, ValueError):
            # ValueError also covers a malformed Content-Length header
            self._respond("invalid json", 400)
            return
        if not isinstance(raw, dict):
            # valid JSON that is not an object ('5', '[1]') must be a
            # 400, not an AttributeError in the handler thread
            self._respond({"message": "body must be a JSON object"}, 400)
            return
        rounds = raw.get("rounds", 16)
        trace_dir = raw.get("trace_dir")
        if not isinstance(rounds, int) or rounds < 1:
            self._respond({"message": "rounds must be an integer >= 1"},
                          400)
            return
        try:
            res = self.generator.engine.request_profile(
                rounds, trace_dir=trace_dir)
        except Exception as e:  # noqa: BLE001 — same jsonified contract
            self._respond({"message": repr(e)}, 500)
            return
        self._respond(res, 200 if res.get("ok") else 409)

    def do_PUT(self):
        if self.path.rstrip("/") != "/api":
            self.send_error(404)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            self._respond("invalid json", 400)
            return
        if raw.get("stream"):
            self._stream_put(raw)
            return
        payload, status = self.generator.put(raw)
        self._respond(payload, status)

    def _stream_put(self, raw):
        """SSE dispatch: headers go out only once the request is
        admitted to the engine queue (validation errors stay plain
        JSON); each generated token is one `data:` event, flushed as it
        books, and the connection closes after the final `done` event —
        EOF is end-of-stream. A write failure means the client
        disconnected: MegatronGenerate.put_stream has already cancelled
        the engine request (slot retired, pages reclaimed); just drop
        the connection."""

        def start_response():
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()

        def write_event(obj, rid=None):
            # the SSE `id:` field carries the engine request id (ISSUE
            # 13): a client-visible stall greps by this id straight to
            # the engine rounds (trace spans, flight-record events) it
            # spanned; EventSource clients surface it as lastEventId
            prefix = f"id: {rid}\n" if rid is not None else ""
            self.wfile.write(
                (prefix + f"data: {json.dumps(obj)}\n\n").encode())
            self.wfile.flush()

        try:
            err = self.generator.put_stream(raw, start_response,
                                            write_event)
        except ConnectionError:
            # client went away mid-stream (broken pipe / reset):
            # put_stream already cancelled the engine request — nothing
            # useful left to send on a dead socket
            self.close_connection = True
            return
        except Exception:
            # a server-side failure after the headers are on the wire
            # reaches the client as a bare EOF with no done event — log
            # it, or it is indistinguishable from a transport bug
            _logger.exception("streaming PUT failed mid-stream")
            self.close_connection = True
            return
        if err is not None:
            self._respond(*err)
        else:
            self.close_connection = True

    def _respond(self, payload, status):
        body = (json.dumps(payload) if isinstance(payload, (dict, list))
                else json.dumps(payload))
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if status in (503, 504):
            # overload (busy device / full queue / deadline shed): tell
            # clients when to come back instead of letting them hammer
            # the socket. With a cost registry on, the engine/router
            # models its backlog drain time (ISSUE 17) — an honest
            # estimate clamped to [1, 60] s; without one this stays the
            # legacy constant 1 s (tests/test_server.py pins it).
            self.send_header("Retry-After", self._retry_after())
        self.end_headers()
        self.wfile.write(data)

    def _retry_after(self) -> str:
        try:
            eng = getattr(self.generator, "engine", None)
            fn = getattr(eng, "retry_after_s", None)
            if fn is not None:
                return str(max(int(round(float(fn()))), 1))
        except Exception:  # noqa: BLE001 — the header is advisory; a
            # modeling hiccup must never turn a 503 into a 500
            pass
        return "1"

    def log_message(self, fmt, *args):  # quiet by default
        pass


class MegatronServer:
    """ref: MegatronServer (text_generation_server.py:234-241). Pass a
    `DecodeEngine` (inference/engine.py) to serve generate requests
    through the continuous-batching queue; its serve loop is started by
    `run` and gracefully drained by `stop`."""

    def __init__(self, model, params, tokenizer, engine=None,
                 request_deadline_s=None, stream_enabled=True):
        self.engine = engine
        self.generator = MegatronGenerate(
            model, params, tokenizer, engine=engine,
            request_deadline_s=request_deadline_s,
            stream_enabled=stream_enabled)
        self._httpd = None

    def run(self, host: str = "0.0.0.0", port: int = 5000,
            block: bool = True):
        if self.engine is not None and self.engine._thread is None:
            self.engine.start()
        handler = type("Handler", (_Handler,), {"generator": self.generator})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        if block:
            self._httpd.serve_forever()
        else:
            t = threading.Thread(target=self._httpd.serve_forever,
                                 daemon=True)
            t.start()
        return self._httpd

    def stop(self):
        """Stop accepting requests, then DRAIN the engine: every
        admitted and queued request finishes before this returns."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        if self.engine is not None:
            self.engine.stop(drain=True)
