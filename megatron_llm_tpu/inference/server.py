"""REST text-generation server, reference API contract.

Parity target: ref megatron/text_generation_server.py — `MegatronGenerate`
(PUT /api, :17-233, including every request-validation message) and
`MegatronServer` (:234-241). The reference needs flask_restful plus a
broadcast to wake the non-rank-0 GPU cohort (:22-29); the JAX build is
single-controller, so a stdlib ThreadingHTTPServer with a generation lock
replaces both (flask isn't in the image; the HTTP surface is identical).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from megatron_llm_tpu.inference.api import (
    beam_search_and_post_process,
    generate_and_post_process,
)

GENERATE_NUM = 0
BEAM_NUM = 1
LOCK = threading.Lock()


class MegatronGenerate:
    """Request validation + dispatch (ref: MegatronGenerate :17-233)."""

    def __init__(self, model, params, tokenizer):
        self.model = model
        self.params = params
        self.tokenizer = tokenizer

    def put(self, raw: dict):
        """Returns (payload, http_status); validation messages mirror the
        reference byte for byte where applicable."""
        if "prompts" not in raw:
            return "prompts argument required", 400
        if "max_len" in raw:
            return "max_len is no longer used.  Replace with tokens_to_generate", 400
        if "sentences" in raw:
            return "sentences is no longer used.  Replace with prompts", 400
        prompts = raw["prompts"]
        if not isinstance(prompts, list):
            return "prompts is not a list of strings", 400
        if len(prompts) == 0:
            return "prompts is empty", 400
        if len(prompts) > 128:
            return "Maximum number of prompts is 128", 400

        tokens_to_generate = raw.get("tokens_to_generate", 64)
        if not isinstance(tokens_to_generate, int):
            return "tokens_to_generate must be an integer greater than 0", 400
        if tokens_to_generate < 0:
            return ("tokens_to_generate must be an integer greater than or "
                    "equal to 0"), 400

        logprobs = raw.get("logprobs", False)
        if not isinstance(logprobs, bool):
            return "logprobs must be a boolean value", 400
        if tokens_to_generate == 0 and not logprobs:
            return "tokens_to_generate=0 implies logprobs should be True", 400

        temperature = raw.get("temperature", 1.0)
        if not isinstance(temperature, (int, float)) or not (
            0.0 < temperature <= 100.0
        ):
            return ("temperature must be a positive number less than or "
                    "equal to 100.0"), 400

        top_k = raw.get("top_k", 0)
        if not isinstance(top_k, int) or not (0 <= top_k <= 1000):
            return "top_k must be an integer equal to or greater than 0 and less than or equal to 1000", 400

        top_p = raw.get("top_p", 0.0)
        if not isinstance(top_p, (int, float)) or not (0.0 <= top_p <= 1.0):
            return "top_p must be less than or equal to 1 and greater than or equal to 0", 400
        if top_p > 0.0 and top_k > 0:
            return "cannot set both top-k and top-p samplings.", 400

        top_p_decay = raw.get("top_p_decay", 0.0)
        top_p_bound = raw.get("top_p_bound", 0.0)
        add_BOS = raw.get("add_BOS", False)
        if not isinstance(add_BOS, bool):
            return "add_BOS must be a boolean value", 400
        if any(len(p) == 0 for p in prompts) and not add_BOS:
            return "Empty prompts require add_BOS=true", 400

        stop_on_double_eol = raw.get("stop_on_double_eol", False)
        stop_on_eol = raw.get("stop_on_eol", False)
        prevent_newline_after_colon = raw.get(
            "prevent_newline_after_colon", False
        )
        random_seed = raw.get("random_seed", -1)
        no_log = raw.get("no_log", False)
        beam_width = raw.get("beam_width", None)
        stop_token = raw.get("stop_token", None)
        length_penalty = raw.get("length_penalty", 1.0)

        with LOCK:  # one generation at a time (ref :186)
            try:
                if beam_width is not None:
                    if not isinstance(beam_width, int) or beam_width < 1:
                        return "beam_width must be integer > 0", 400
                    if len(prompts) > 1:
                        return "When doing beam_search, batch size must be 1", 400
                    texts, segments, scores, _ = beam_search_and_post_process(
                        self.model, self.params, self.tokenizer, prompts,
                        tokens_to_generate=tokens_to_generate,
                        beam_size=beam_width,
                        add_BOS=add_BOS,
                        stop_token=stop_token,
                        num_return_gen=beam_width,
                        length_penalty=length_penalty,
                        prevent_newline_after_colon=prevent_newline_after_colon,
                    )
                    return {
                        "text": texts,
                        "segments": segments,
                        "scores": scores.tolist(),
                    }, 200
                texts, segments, lp, _ = generate_and_post_process(
                    self.model, self.params, self.tokenizer, prompts,
                    tokens_to_generate=tokens_to_generate,
                    return_output_log_probs=logprobs,
                    top_k_sampling=top_k,
                    top_p_sampling=top_p,
                    top_p_decay=top_p_decay,
                    top_p_bound=top_p_bound,
                    temperature=temperature,
                    add_BOS=add_BOS,
                    stop_on_eol=stop_on_eol,
                    stop_on_double_eol=stop_on_double_eol,
                    prevent_newline_after_colon=prevent_newline_after_colon,
                    random_seed=random_seed,
                )
                return {
                    "text": texts,
                    "segments": segments,
                    "logprobs": lp.tolist() if lp is not None else None,
                }, 200
            except Exception as e:  # ref returns jsonified error (:230)
                return {"message": repr(e)}, 500


class _Handler(BaseHTTPRequestHandler):
    generator: Optional[MegatronGenerate] = None

    def do_GET(self):
        # the reference serves its static generation UI at /
        # (megatron/static/index.html via flask static routing)
        if self.path in ("/", "/index.html"):
            from megatron_llm_tpu.inference.static_ui import INDEX_HTML

            data = INDEX_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        self.send_error(404)

    def do_PUT(self):
        if self.path.rstrip("/") != "/api":
            self.send_error(404)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            self._respond("invalid json", 400)
            return
        payload, status = self.generator.put(raw)
        self._respond(payload, status)

    def _respond(self, payload, status):
        body = (json.dumps(payload) if isinstance(payload, (dict, list))
                else json.dumps(payload))
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):  # quiet by default
        pass


class MegatronServer:
    """ref: MegatronServer (text_generation_server.py:234-241)."""

    def __init__(self, model, params, tokenizer):
        self.generator = MegatronGenerate(model, params, tokenizer)
        self._httpd = None

    def run(self, host: str = "0.0.0.0", port: int = 5000,
            block: bool = True):
        handler = type("Handler", (_Handler,), {"generator": self.generator})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        if block:
            self._httpd.serve_forever()
        else:
            t = threading.Thread(target=self._httpd.serve_forever,
                                 daemon=True)
            t.start()
        return self._httpd

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
