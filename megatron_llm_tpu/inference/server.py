"""REST text-generation server, reference API contract.

Parity target: ref megatron/text_generation_server.py — `MegatronGenerate`
(PUT /api, :17-233, including every request-validation message) and
`MegatronServer` (:234-241). The reference needs flask_restful plus a
broadcast to wake the non-rank-0 GPU cohort (:22-29); the JAX build is
single-controller, so a stdlib ThreadingHTTPServer replaces both (flask
isn't in the image; the HTTP surface is identical).

Dispatch (ISSUE 3): with a `DecodeEngine` attached, generate requests
are ENQUEUED — each prompt becomes one engine request carrying its own
tokens_to_generate / sampling knobs, admitted mid-flight into free
slots, so concurrent PUTs batch together instead of serializing. A full
queue returns 503 + Retry-After. Score-only, beam and the knobs the
engine does not speak (prevent_newline_after_colon, top_p_decay) take
the whole-batch path under a NON-BLOCKING device lock: a second
concurrent request gets 503 + Retry-After instead of stacking device
work behind a blocked thread (two unlocked concurrent PUTs used to race
on the same device; stacking them hid the overload from the client).
`MegatronServer.stop()` drains the engine before returning.

GET /metrics (engine-attached servers) returns the live
`DecodeEngine.counters()` dict — slot occupancy, queue depth, page
accounting, tok/s, and the ISSUE-4 latency gauges (serve_ttft_p50_ms /
serve_ttft_p95_ms / serve_decode_p95_ms) — as JSON.

GET /health (ISSUE 5) is the load-balancer probe: 200 while the serving
path can take traffic, 503 once the engine's serve loop died poisoned
(`DecodeEngine._broken`) or its thread stopped, with the engine's
liveness snapshot (alive / broken / queue_depth / slots_busy) as the
body. Engineless servers always answer 200.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from megatron_llm_tpu.inference.api import (
    beam_search_and_post_process,
    generate_and_post_process,
)

GENERATE_NUM = 0
BEAM_NUM = 1
LOCK = threading.Lock()
BUSY_MSG = "server is busy processing another request"
QUEUE_FULL_MSG = "generation queue is full"


class MegatronGenerate:
    """Request validation + dispatch (ref: MegatronGenerate :17-233)."""

    def __init__(self, model, params, tokenizer, engine=None,
                 request_deadline_s=None):
        self.model = model
        self.params = params
        self.tokenizer = tokenizer
        self.engine = engine
        # server-wide wall-clock budget applied to every engine request
        # (DecodeEngine deadline semantics: expiry fails the waiter and
        # reclaims the slot's pages); None = no deadline
        self.request_deadline_s = request_deadline_s

    def put(self, raw: dict):
        """Returns (payload, http_status); validation messages mirror the
        reference byte for byte where applicable."""
        if "prompts" not in raw:
            return "prompts argument required", 400
        if "max_len" in raw:
            return "max_len is no longer used.  Replace with tokens_to_generate", 400
        if "sentences" in raw:
            return "sentences is no longer used.  Replace with prompts", 400
        prompts = raw["prompts"]
        if not isinstance(prompts, list):
            return "prompts is not a list of strings", 400
        if len(prompts) == 0:
            return "prompts is empty", 400
        if len(prompts) > 128:
            return "Maximum number of prompts is 128", 400

        tokens_to_generate = raw.get("tokens_to_generate", 64)
        if not isinstance(tokens_to_generate, int):
            return "tokens_to_generate must be an integer greater than 0", 400
        if tokens_to_generate < 0:
            return ("tokens_to_generate must be an integer greater than or "
                    "equal to 0"), 400

        logprobs = raw.get("logprobs", False)
        if not isinstance(logprobs, bool):
            return "logprobs must be a boolean value", 400
        if tokens_to_generate == 0 and not logprobs:
            return "tokens_to_generate=0 implies logprobs should be True", 400

        temperature = raw.get("temperature", 1.0)
        if not isinstance(temperature, (int, float)) or not (
            0.0 < temperature <= 100.0
        ):
            return ("temperature must be a positive number less than or "
                    "equal to 100.0"), 400

        top_k = raw.get("top_k", 0)
        if not isinstance(top_k, int) or not (0 <= top_k <= 1000):
            return "top_k must be an integer equal to or greater than 0 and less than or equal to 1000", 400

        top_p = raw.get("top_p", 0.0)
        if not isinstance(top_p, (int, float)) or not (0.0 <= top_p <= 1.0):
            return "top_p must be less than or equal to 1 and greater than or equal to 0", 400
        if top_p > 0.0 and top_k > 0:
            return "cannot set both top-k and top-p samplings.", 400

        top_p_decay = raw.get("top_p_decay", 0.0)
        top_p_bound = raw.get("top_p_bound", 0.0)
        add_BOS = raw.get("add_BOS", False)
        if not isinstance(add_BOS, bool):
            return "add_BOS must be a boolean value", 400
        if any(len(p) == 0 for p in prompts) and not add_BOS:
            return "Empty prompts require add_BOS=true", 400

        stop_on_double_eol = raw.get("stop_on_double_eol", False)
        stop_on_eol = raw.get("stop_on_eol", False)
        prevent_newline_after_colon = raw.get(
            "prevent_newline_after_colon", False
        )
        random_seed = raw.get("random_seed", -1)
        no_log = raw.get("no_log", False)
        beam_width = raw.get("beam_width", None)
        stop_token = raw.get("stop_token", None)
        length_penalty = raw.get("length_penalty", 1.0)

        if beam_width is not None:
            if not isinstance(beam_width, int) or beam_width < 1:
                return "beam_width must be integer > 0", 400
            if len(prompts) > 1:
                return "When doing beam_search, batch size must be 1", 400

        # continuous-batching dispatch: everything the engine speaks goes
        # through its queue (per-request knobs, slot-level admission); the
        # engine-ineligible residue (score-only, beam, pnac/top_p_decay)
        # keeps the whole-batch path below
        if (self.engine is not None and beam_width is None
                and tokens_to_generate > 0
                and not prevent_newline_after_colon
                and top_p_decay == 0.0):
            resp = self._put_engine(
                prompts, tokens_to_generate, logprobs, top_k, top_p,
                temperature, add_BOS, random_seed,
            )
            if resp is not None:
                return resp
            # None: the request exceeds the engine's max_context/pool —
            # a capability the whole-batch path still has; fall through

        # one whole-batch generation at a time (ref :186) — but NON-
        # blocking: a concurrent request is overload, and the honest
        # answer is 503 + Retry-After, not device work stacking up
        # behind a blocked handler thread
        if not LOCK.acquire(blocking=False):
            return {"message": BUSY_MSG}, 503
        try:
            try:
                if beam_width is not None:
                    texts, segments, scores, _ = beam_search_and_post_process(
                        self.model, self.params, self.tokenizer, prompts,
                        tokens_to_generate=tokens_to_generate,
                        beam_size=beam_width,
                        add_BOS=add_BOS,
                        stop_token=stop_token,
                        num_return_gen=beam_width,
                        length_penalty=length_penalty,
                        prevent_newline_after_colon=prevent_newline_after_colon,
                    )
                    return {
                        "text": texts,
                        "segments": segments,
                        "scores": scores.tolist(),
                    }, 200
                texts, segments, lp, _ = generate_and_post_process(
                    self.model, self.params, self.tokenizer, prompts,
                    tokens_to_generate=tokens_to_generate,
                    return_output_log_probs=logprobs,
                    top_k_sampling=top_k,
                    top_p_sampling=top_p,
                    top_p_decay=top_p_decay,
                    top_p_bound=top_p_bound,
                    temperature=temperature,
                    add_BOS=add_BOS,
                    stop_on_eol=stop_on_eol,
                    stop_on_double_eol=stop_on_double_eol,
                    prevent_newline_after_colon=prevent_newline_after_colon,
                    random_seed=random_seed,
                )
                return {
                    "text": texts,
                    "segments": segments,
                    "logprobs": lp.tolist() if lp is not None else None,
                }, 200
            except Exception as e:  # ref returns jsonified error (:230)
                return {"message": repr(e)}, 500
        finally:
            LOCK.release()

    def _put_engine(self, prompts, tokens_to_generate, logprobs, top_k,
                    top_p, temperature, add_BOS, random_seed):
        """Queue each prompt as one engine request and wait for all of
        them; the response shape matches the whole-batch path (ragged
        logprobs: one list per prompt). Returns None — caller falls back
        to the whole-batch path — when any prompt exceeds the engine's
        max_context or page pool (those limits don't exist there)."""
        import numpy as np

        from megatron_llm_tpu.inference.engine import QueueFull
        from megatron_llm_tpu.inference.tokenization import (
            detokenize_generations,
        )

        tok = self.tokenizer
        prompt_ids = []
        for p in prompts:
            ids = tok.tokenize(p)
            if add_BOS:
                ids = [tok.bos] + ids
            prompt_ids.append(ids)
        eng = self.engine
        pool_tokens = (eng.num_pages - 1) * eng.page_size
        if any(len(ids) + tokens_to_generate
               > min(eng.max_context, pool_tokens)
               for ids in prompt_ids):
            return None
        reqs = []
        try:
            for i, ids in enumerate(prompt_ids):
                if random_seed >= 0:
                    seed = random_seed + i  # decorrelate rows, keep
                    # request-level determinism (engine RNG is per
                    # request, not per batch position)
                else:
                    import os as _os

                    seed = int.from_bytes(_os.urandom(4), "little")
                try:
                    reqs.append(self.engine.submit(
                        ids, tokens_to_generate,
                        top_k=top_k, top_p=top_p, temperature=temperature,
                        seed=seed, return_log_probs=logprobs,
                        use_eod_for_early_termination=True,
                        deadline_s=self.request_deadline_s,
                    ))
                except QueueFull:
                    # admitted prefixes of THIS PUT still complete; the
                    # client retries the whole request after Retry-After
                    return {"message": QUEUE_FULL_MSG}, 503
            rows, lps = [], []
            for r in reqs:
                try:
                    toks, lp = r.result(timeout=600.0)
                except TimeoutError as e:
                    # per-request deadline expiry (engine deadline_s) is
                    # overload shed, not an engine fault: 504 +
                    # Retry-After so clients and monitoring can tell it
                    # from a real 5xx crash
                    return {"message": repr(e)}, 504
                rows.append(toks)
                lps.append(lp)
            max_len = max(len(t) for t in rows)
            buf = np.full((len(rows), max_len), tok.eod, np.int32)
            for i, t in enumerate(rows):
                buf[i, : len(t)] = t
            lengths = np.asarray([len(t) for t in rows], np.int32)
            texts, segments = detokenize_generations(
                tok, buf, lengths, return_segments=True)
            return {
                "text": texts,
                "segments": segments,
                "logprobs": ([list(map(float, l)) for l in lps]
                             if logprobs else None),
            }, 200
        except Exception as e:  # same jsonified-error contract (:230)
            return {"message": repr(e)}, 500


class _Handler(BaseHTTPRequestHandler):
    generator: Optional[MegatronGenerate] = None

    def do_GET(self):
        # the reference serves its static generation UI at /
        # (megatron/static/index.html via flask static routing)
        if self.path in ("/", "/index.html"):
            from megatron_llm_tpu.inference.static_ui import INDEX_HTML

            data = INDEX_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if self.path.rstrip("/") == "/health":
            # liveness/readiness probe (ISSUE 5): 200 while the serving
            # path can take traffic, 503 once the engine's serve loop
            # died poisoned (DecodeEngine._broken) or its thread is gone
            # — a load balancer drains the replica instead of feeding
            # requests into hung waiters. Engineless (whole-batch-only)
            # servers are always 200: every PUT runs inline.
            eng = self.generator.engine
            if eng is None:
                self._respond({"status": "ok", "engine": None}, 200)
                return
            h = eng.health()
            healthy = h["broken"] is None and h["alive"]
            self._respond(
                {"status": "ok" if healthy else "unhealthy", "engine": h},
                200 if healthy else 503)
            return
        if self.path.rstrip("/") == "/metrics":
            # live engine counters (DecodeEngine.counters — occupancy,
            # queue depth, pages, tok/s, and the latency gauges
            # serve_ttft_p50/p95_ms + serve_decode_p95_ms) as JSON; the
            # same dict the timers-gauge export carries, so dashboards
            # and curl read one schema. 404 when no engine is attached
            # (whole-batch-only server has no per-request gauges).
            if self.generator.engine is None:
                self.send_error(404)
                return
            self._respond(self.generator.engine.counters(), 200)
            return
        self.send_error(404)

    def do_PUT(self):
        if self.path.rstrip("/") != "/api":
            self.send_error(404)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            self._respond("invalid json", 400)
            return
        payload, status = self.generator.put(raw)
        self._respond(payload, status)

    def _respond(self, payload, status):
        body = (json.dumps(payload) if isinstance(payload, (dict, list))
                else json.dumps(payload))
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if status in (503, 504):
            # overload (busy device / full queue / deadline shed): tell
            # clients when to come back instead of letting them hammer
            # the socket
            self.send_header("Retry-After", "1")
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):  # quiet by default
        pass


class MegatronServer:
    """ref: MegatronServer (text_generation_server.py:234-241). Pass a
    `DecodeEngine` (inference/engine.py) to serve generate requests
    through the continuous-batching queue; its serve loop is started by
    `run` and gracefully drained by `stop`."""

    def __init__(self, model, params, tokenizer, engine=None,
                 request_deadline_s=None):
        self.engine = engine
        self.generator = MegatronGenerate(
            model, params, tokenizer, engine=engine,
            request_deadline_s=request_deadline_s)
        self._httpd = None

    def run(self, host: str = "0.0.0.0", port: int = 5000,
            block: bool = True):
        if self.engine is not None and self.engine._thread is None:
            self.engine.start()
        handler = type("Handler", (_Handler,), {"generator": self.generator})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        if block:
            self._httpd.serve_forever()
        else:
            t = threading.Thread(target=self._httpd.serve_forever,
                                 daemon=True)
            t.start()
        return self._httpd

    def stop(self):
        """Stop accepting requests, then DRAIN the engine: every
        admitted and queued request finishes before this returns."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        if self.engine is not None:
            self.engine.stop(drain=True)
