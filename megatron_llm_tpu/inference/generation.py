"""Autoregressive generation engine: scoring, sampling decode, beam search.

Parity target: ref megatron/text_generation/generation.py —
`score_and_return_on_first_stage` (:20), the incremental KV-cached decode
loop `generate_tokens_probs_and_return_on_first_stage` (:89-286) and
`beam_search_and_return_on_first_stage` (:288-429).

TPU-first structure: the reference drives a per-token Python loop issuing
one forward per context length with pipeline broadcasts between stages.
Here the whole decode is ONE jitted program: a prefill forward over the
common prompt prefix, then a `lax.while_loop` over single-token steps
against the preallocated KV cache — token selection, teacher-forcing of
still-in-prompt rows, logprob gathering and eod early-termination all live
inside the loop, so there is no per-token host round-trip. The pipeline
broadcast machinery (ref text_generation/communication.py) has no
analogue: under GSPMD the logits land wherever the sampling runs.

Per-step attention inside the loop body runs the Pallas decode-attention
kernel by default on TPU (ops/decode_attention.py, routed by
models/attention.py's cached branches): the per-layer (b, g, T, d)
caches stream through VMEM at HBM line rate with in-kernel cache-length
masking, instead of XLA's under-bandwidth matvec loops. The XLA path
remains the fallback below `cfg.decode_attn_min_cache` and off-TPU;
tokens and logprobs are exact-match between the two
(tests/test_decode_attention.py).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from megatron_llm_tpu.analysis.contracts import (
    CompileContract,
    register_contract,
)
from megatron_llm_tpu.inference.sampling import (
    NEG_INF,
    modify_logits_for_top_k,
    modify_logits_for_top_p,
)

# Module-level jits trace per (static, shape) key in jax's own call
# cache; `bucket_prefill_len` bounds the key space and the AOT audit
# (analysis/audit.py) lowers generate_tokens at the reference config.
register_contract(CompileContract(
    name="generate.tokens",
    max_variants=None,  # counted by jax's jit cache, bounded by
    # bucket_prefill_len at every caller (api.py, tests pin the count)
    collectives={"single": frozenset()},
    tmp_bytes_budget=4 << 20,  # 321 KB measured at the audit config
    notes="the whole-batch decode loop: prefill + lax.while_loop; "
          "variant growth is the prefill-bucket/statics key space"))
register_contract(CompileContract(
    name="generate.beam",
    max_variants=None,  # _beam_step keys on (beam, V) shapes,
    # _beam_advance on the model static — both module-level caches
    collectives=None,  # beam rides the same forward as generate.tokens
    notes="beam-search helpers (_beam_step, _beam_advance)"))


class GenerateOutput(NamedTuple):
    tokens: jnp.ndarray  # (b, max_len) prompt + generated
    lengths: jnp.ndarray  # (b,) total generated length incl. prompt
    log_probs: Optional[jnp.ndarray]  # (b, max_len - 1) fp32 or None


def bucket_prefill_len(min_len: int) -> int:
    """Bucket a prompt's prefill length DOWN to a bounded set of compile
    shapes: multiples of 64 at >= 64, powers of two below (1,2,4,...,32).
    `prefill_len` is a jit static arg of `generate_tokens` (and of the
    serving engine's prefill), so every distinct value is a distinct
    compiled executable — raw short-prompt lengths were minting up to 63
    of them (ISSUE 3 satellite). Bucketing DOWN is always safe: the
    positions past the bucket are teacher-forced by the decode loop, so
    tokens/logprobs are unchanged."""
    if min_len >= 64:
        return (min_len // 64) * 64
    return 1 << (max(min_len, 1).bit_length() - 1)


def select_next_token(
    logits,  # (b, V) fp32-castable
    prev_token,  # (b,) int32
    step_rng,
    cur_top_p,
    *,
    greedy: bool,
    top_k: int,
    top_p: float,
    temperature: float,
    vocab_size=None,
    prevent_newline_after_colon_ids=None,
):
    """One sampling decision (ref: generation.py:174-237 sampling block) —
    shared by the single-mesh decode loop and the pp-pipelined decode."""
    logits = logits.astype(jnp.float32)
    if prevent_newline_after_colon_ids is not None:
        # ref :191: disable "\n" right after ":"
        colon_id, newline_id = prevent_newline_after_colon_ids
        hit = prev_token == colon_id
        logits = jnp.where(
            hit[:, None]
            & (jnp.arange(logits.shape[-1]) == newline_id)[None, :],
            NEG_INF, logits,
        )
    if vocab_size is not None and vocab_size < logits.shape[-1]:
        pad = jnp.arange(logits.shape[-1]) >= vocab_size
        logits = jnp.where(pad[None, :], NEG_INF, logits)
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if temperature != 1.0:
        logits = logits / temperature
    if top_k > 1:
        logits = modify_logits_for_top_k(logits, top_k)
    elif top_p > 0.0:
        logits = modify_logits_for_top_p(logits, cur_top_p)
    return jax.random.categorical(step_rng, logits, axis=-1).astype(jnp.int32)


def score_tokens(model, params, tokens: jnp.ndarray) -> jnp.ndarray:
    """Log-probs of each provided next token (ref:
    score_and_return_on_first_stage generation.py:20-86).
    Returns (b, s-1): lp[:, i] = log P(tokens[:, i+1] | tokens[:, :i+1])."""
    logits, _ = model.forward(params, tokens[:, :-1])
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(
        lp, tokens[:, 1:, None].astype(jnp.int32), axis=-1
    ).squeeze(-1)


# graft-contract: generate.tokens
@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "prefill_len", "top_k", "top_p", "temperature",
        "vocab_size", "termination_id", "return_log_probs",
        "use_eod_for_early_termination", "top_p_decay", "top_p_bound",
        "prevent_newline_after_colon_ids",
    ),
)
def generate_tokens(
    model,
    params,
    tokens: jnp.ndarray,  # (b, max_len) int32, prompts left-aligned + padded
    lengths: jnp.ndarray,  # (b,) prompt lengths
    prefill_len: int,  # static; <= min(lengths), >= 1
    rng: Optional[jax.Array] = None,
    top_k: int = 0,
    top_p: float = 0.0,
    top_p_decay: float = 0.0,
    top_p_bound: float = 0.0,
    temperature: float = 1.0,
    vocab_size: Optional[int] = None,
    termination_id: Optional[int] = None,
    return_log_probs: bool = False,
    use_eod_for_early_termination: bool = True,
    prevent_newline_after_colon_ids: Optional[Tuple[int, int]] = None,
) -> GenerateOutput:
    """The main generation function (ref: generation.py:89-286).

    Rows whose prompt extends past the current position are teacher-forced
    (ref :209-211 `started` mask); generation for a row starts at its own
    prompt end. Decode runs until max_len or until every started row has
    emitted `termination_id` (ref :239-263).
    """
    b, max_len = tokens.shape
    tokens = tokens.astype(jnp.int32)
    greedy = top_k == 1 or rng is None
    if rng is None:
        rng = jax.random.key(0)  # unused on the greedy path

    # one-time decode layout: per-layer standalone weights (no per-token
    # stack slicing, flat GLU matvec) + per-layer (b, g, T, d) caches —
    # see prepare_decode_params / init_kv_caches(layout="layers");
    # outside the token loop by construction
    if hasattr(model, "prepare_decode_params"):
        params = model.prepare_decode_params(params)
        caches = model.init_kv_caches(b, max_len, layout="layers")
    else:
        caches = model.init_kv_caches(b, max_len)

    log_probs = jnp.zeros((b, max_len - 1), jnp.float32)

    def gather_lp(logits, targets):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return jnp.take_along_axis(lp, targets[..., None], axis=-1).squeeze(-1)

    # ---- prefill the common prefix (one big causal forward) --------------
    logits, caches = model.forward(
        params, tokens[:, :prefill_len], kv_caches=caches
    )
    if return_log_probs:
        # positions 0..prefill_len-2 predict tokens 1..prefill_len-1
        log_probs = jax.lax.dynamic_update_slice(
            log_probs, gather_lp(logits[:, :-1], tokens[:, 1:prefill_len]),
            (0, 0),
        )
    last_logits = logits[:, -1]  # predicts position prefill_len

    def select_token(logits, t, prev_token, step_rng, cur_top_p):
        return select_next_token(
            logits, prev_token, step_rng, cur_top_p, greedy=greedy,
            top_k=top_k, top_p=top_p, temperature=temperature,
            vocab_size=vocab_size,
            prevent_newline_after_colon_ids=prevent_newline_after_colon_ids,
        )

    # ---- single-token decode steps ---------------------------------------
    # carry: (t, tokens, caches, last_logits, log_probs, done, gen_lengths,
    #         cur_top_p)
    def cond(carry):
        t, _, _, _, _, done, _, _ = carry
        keep_going = t < max_len
        if use_eod_for_early_termination and termination_id is not None:
            keep_going &= ~jnp.all(done)
        return keep_going

    def body(carry):
        t, toks, caches, last_logits, lps, done, gen_lens, cur_top_p = carry
        step_rng = jax.random.fold_in(rng, t)
        prev_token = jax.lax.dynamic_index_in_dim(toks, t - 1, axis=1,
                                                  keepdims=False)
        new_sample = select_token(last_logits, t, prev_token, step_rng,
                                  cur_top_p)
        started = lengths <= t  # ref :209 — past this row's prompt?
        prompt_tok = jax.lax.dynamic_index_in_dim(toks, t, axis=1,
                                                  keepdims=False)
        chosen = jnp.where(started, new_sample, prompt_tok)
        toks = jax.lax.dynamic_update_slice(toks, chosen[:, None], (0, t))

        if return_log_probs:
            lps = jax.lax.dynamic_update_slice(
                lps, gather_lp(last_logits, chosen)[:, None], (0, t - 1)
            )

        # eod bookkeeping (ref :239-263)
        if termination_id is not None:
            done_token = (chosen == termination_id) & started
            just_finished = done_token & ~done
            gen_lens = jnp.where(just_finished, t + 1, gen_lens)
            done = done | done_token

        if top_p > 0.0 and top_p_decay > 0.0:
            cur_top_p = jnp.maximum(cur_top_p * top_p_decay,
                                    top_p_bound)

        # next step's logits from the KV-cached single-token forward
        logits, caches = model.forward(
            params, chosen[:, None], kv_caches=caches
        )
        return (t + 1, toks, caches, logits[:, -1], lps, done, gen_lens,
                cur_top_p)

    carry = (
        jnp.asarray(prefill_len, jnp.int32),
        tokens,
        caches,
        last_logits,
        log_probs,
        jnp.zeros((b,), bool),
        jnp.full((b,), max_len, jnp.int32),
        jnp.float32(top_p),
    )
    _, tokens, _, _, log_probs, _, gen_lens, _ = jax.lax.while_loop(
        cond, body, carry
    )
    return GenerateOutput(
        tokens=tokens,
        lengths=gen_lens,
        log_probs=log_probs if return_log_probs else None,
    )


# ---------------------------------------------------------------------------
# Beam search (ref: beam_search_and_return_on_first_stage generation.py:288
# + BeamHypotheses beam_utils.py:19)
# ---------------------------------------------------------------------------


# graft-contract: generate.beam
@functools.partial(jax.jit, static_argnames=("beam_size", "vocab_size"))
def _beam_step(params, last_logits, scores, beam_size, vocab_size):
    """Top 2*beam (score, flat-index) candidates (ref: generation.py:336-357).
    Module-level so repeated beam_search calls hit the jit cache."""
    lp = jax.nn.log_softmax(last_logits.astype(jnp.float32), axis=-1)
    if vocab_size is not None and vocab_size < lp.shape[-1]:
        pad = jnp.arange(lp.shape[-1]) >= vocab_size
        lp = jnp.where(pad[None, :], NEG_INF, lp)
    total = lp + scores[:, None]  # (beam, V)
    return jax.lax.top_k(total.reshape(-1), 2 * beam_size)


# graft-contract: generate.beam
@functools.partial(jax.jit, static_argnames=("model",), donate_argnums=(3,))
def _beam_advance(model, params, toks, caches, beam_idx, token_idx, t):
    """Reorder beams, bank the chosen tokens, run one KV-cached step
    (ref: generation.py:359-398 beam reorder + forward)."""
    toks = jnp.take(toks, beam_idx, axis=0)
    # cache batch axis: 0 in the per-layer (b, g, T, d) decode layout,
    # 1 in the stacked (L, b, T, g, d) one
    b_axis = 0 if "k_layers" in caches else 1
    caches = jax.tree.map(
        lambda c: jnp.take(c, beam_idx, axis=b_axis) if c.ndim >= 2 else c,
        caches,
    )
    toks = jax.lax.dynamic_update_slice(
        toks, token_idx[:, None].astype(jnp.int32), (0, t)
    )
    logits, caches = model.forward(
        params, token_idx[:, None].astype(jnp.int32), kv_caches=caches
    )
    return toks, caches, logits[:, -1]


class BeamHypotheses:
    """Sorted pool of finished hypotheses (ref: beam_utils.py:19-60)."""

    def __init__(self, num_beams: int, length_penalty: float = 1.0,
                 early_stopping: bool = False):
        self.num_beams = num_beams
        self.length_penalty = length_penalty
        self.early_stopping = early_stopping
        self.beams: list = []
        self.worst_score = 1e9

    def __len__(self):
        return len(self.beams)

    def add(self, hyp, sum_logprobs: float):
        score = sum_logprobs / max(len(hyp), 1) ** self.length_penalty
        if len(self) < self.num_beams or score > self.worst_score:
            self.beams.append((score, hyp))
            if len(self) > self.num_beams:
                sorted_scores = sorted(
                    (s, idx) for idx, (s, _) in enumerate(self.beams)
                )
                del self.beams[sorted_scores[0][1]]
                self.worst_score = sorted_scores[1][0]
            else:
                self.worst_score = min(score, self.worst_score)

    def is_done(self, best_sum_logprobs: float, cur_len: int) -> bool:
        if len(self) < self.num_beams:
            return False
        if self.early_stopping:
            return True
        return self.worst_score >= (
            best_sum_logprobs / cur_len ** self.length_penalty
        )


def beam_search(
    model,
    params,
    tokens: jnp.ndarray,  # (1, max_len) prompt + padding
    prompt_length: int,
    beam_size: int,
    stop_token: int,
    num_return_gen: int = 1,
    length_penalty: float = 1.0,
    vocab_size: Optional[int] = None,
    max_new_tokens: Optional[int] = None,
):
    """Batch-1 beam search (the reference asserts batch==1 too,
    generation.py:295). Host loop over positions with jitted single-token
    steps; beam bookkeeping mirrors BeamHypotheses.

    `max_new_tokens` bounds the decode independently of the buffer's
    compile-shape padding, so generations never exceed the requested
    budget (the buffer is padded up to a multiple of 64 for jit-cache
    stability — without the bound the loop would run to the pad).

    Returns (tokens (num_return_gen, out_len), scores (num_return_gen,)).
    """
    import numpy as np

    assert tokens.shape[0] == 1, "beam search: batch size must be 1"
    max_len = tokens.shape[1]
    if max_new_tokens is not None:
        max_len = min(max_len, prompt_length + max_new_tokens)
    tokens = jnp.broadcast_to(tokens, (beam_size,) + tokens.shape[1:]).astype(
        jnp.int32
    )

    if hasattr(model, "prepare_decode_params"):
        params = model.prepare_decode_params(params)
        caches = model.init_kv_caches(beam_size, max_len, layout="layers")
    else:
        caches = model.init_kv_caches(beam_size, max_len)
    logits, caches = model.forward(
        params, tokens[:, :prompt_length], kv_caches=caches
    )
    last_logits = logits[:, -1]

    def step(params, last_logits, scores):
        return _beam_step(params, last_logits, scores, beam_size, vocab_size)

    def advance(params, toks, caches, beam_idx, token_idx, t):
        return _beam_advance(
            model, params, toks, caches, beam_idx, token_idx, t
        )

    vocab = last_logits.shape[-1]
    scores = jnp.concatenate(
        [jnp.zeros((1,)), jnp.full((beam_size - 1,), NEG_INF)]
    )  # first step: all beams identical, only beam 0 counts (ref :330-334)
    hyps = BeamHypotheses(beam_size, length_penalty)
    done = False

    for t in range(prompt_length, max_len):
        best_scores, best_idx = step(params, last_logits, scores)
        best_scores = np.asarray(best_scores)
        best_idx = np.asarray(best_idx)

        next_beams = []  # (score, beam, token)
        for sc, idx in zip(best_scores, best_idx):
            beam, tok = divmod(int(idx), vocab)
            if tok == stop_token:
                hyp = np.asarray(tokens[beam, prompt_length:t])
                hyps.add(hyp, float(sc))
            else:
                next_beams.append((float(sc), beam, tok))
            if len(next_beams) == beam_size:
                break
        if hyps.is_done(float(best_scores[0]), t - prompt_length + 1):
            done = True
            break
        if not next_beams:
            break
        beam_idx = jnp.asarray([b for _, b, _ in next_beams], jnp.int32)
        token_idx = jnp.asarray([tk for _, _, tk in next_beams], jnp.int32)
        scores = jnp.asarray([s for s, _, _ in next_beams], jnp.float32)
        tokens, caches, last_logits = advance(
            params, tokens, caches, beam_idx, token_idx, t
        )

    if not done:
        # out of length: finalize open beams (ref :402-407)
        for i in range(beam_size):
            hyp = np.asarray(tokens[i, prompt_length:max_len])
            hyps.add(hyp, float(scores[i]))

    best = sorted(hyps.beams, key=lambda x: -x[0])[:num_return_gen]
    prompt = np.asarray(tokens[0, :prompt_length])
    out_tokens = []
    out_scores = []
    for score, hyp in best:
        seq = np.concatenate([prompt, np.asarray(hyp, np.int32)])
        out_tokens.append(seq)
        out_scores.append(score)
    pad_to = max(len(s) for s in out_tokens)
    out = np.full((len(out_tokens), pad_to), stop_token, np.int32)
    for i, s in enumerate(out_tokens):
        out[i, : len(s)] = s
    return jnp.asarray(out), jnp.asarray(out_scores, jnp.float32)
