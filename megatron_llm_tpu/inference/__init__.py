from megatron_llm_tpu.inference.api import (  # noqa: F401
    beam_search_and_post_process,
    generate_and_post_process,
)
from megatron_llm_tpu.inference.generation import (  # noqa: F401
    beam_search,
    generate_tokens,
    score_tokens,
)
from megatron_llm_tpu.inference.sampling import sample  # noqa: F401
from megatron_llm_tpu.inference.engine import (  # noqa: F401
    DecodeEngine,
    EngineRequest,
    QueueFull,
)
from megatron_llm_tpu.inference.prefix_cache import (  # noqa: F401
    PrefixCache,
)
from megatron_llm_tpu.inference.router import (  # noqa: F401
    EngineReplica,
    HTTPReplica,
    ReplicaRouter,
)
from megatron_llm_tpu.inference.chaos import (  # noqa: F401
    ChaosFault,
    ChaosPolicy,
)
from megatron_llm_tpu.inference.fleet import (  # noqa: F401
    FleetController,
)
