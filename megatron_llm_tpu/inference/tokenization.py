"""Prompt tokenization / generation detokenization.

Parity target: ref megatron/text_generation/tokenization.py —
`tokenize_prompts` (:47, pad to max prompt + tokens_to_generate) and
`detokenize_generations` (:13, with per-token segments). The reference
broadcasts tokenized prompts from rank 0; single-controller JAX needs no
broadcast.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def tokenize_prompts(
    tokenizer,
    prompts: List[str],
    tokens_to_generate: int,
    add_BOS: bool = False,
    pad_to_multiple: int = 64,
) -> Tuple[np.ndarray, np.ndarray]:
    """-> (tokens (b, max_len) int32 right-padded with eod, lengths (b,)).

    max_len = max prompt length + tokens_to_generate, rounded up to
    `pad_to_multiple` so the jitted decode loop compiles for a bounded set
    of shapes (the reference pads exactly, :86-95, and recompiles nothing
    because eager torch doesn't care).
    """
    if add_BOS:
        bos = getattr(tokenizer, "bos", None)
        assert bos is not None, "tokenizer has no BOS token"
        prompt_ids = [[bos] + tokenizer.tokenize(p) for p in prompts]
    else:
        prompt_ids = [tokenizer.tokenize(p) for p in prompts]
    lengths = np.asarray([len(p) for p in prompt_ids], np.int32)
    max_len = int(lengths.max()) + tokens_to_generate
    if pad_to_multiple > 1:
        max_len = ((max_len + pad_to_multiple - 1) // pad_to_multiple
                   ) * pad_to_multiple
    pad_id = tokenizer.eod
    tokens = np.full((len(prompts), max_len), pad_id, np.int32)
    for i, ids in enumerate(prompt_ids):
        tokens[i, : len(ids)] = ids
    return tokens, lengths


def detokenize_generations(
    tokenizer,
    tokens: np.ndarray,  # (b, s)
    lengths: np.ndarray,  # (b,) valid lengths incl. prompt
    return_segments: bool = False,
):
    """-> (texts, [segments]) (ref: detokenize_generations :13-44)."""
    texts = []
    segments: List[List[str]] = []
    for row, n in zip(np.asarray(tokens), np.asarray(lengths)):
        ids = [int(t) for t in row[: int(n)]]
        texts.append(tokenizer.detokenize(ids))
        if return_segments:
            seg = []
            for tid in ids:
                # per-token surface form (ref uses tokenizer-specific
                # decoder lookups, :27-39)
                seg.append(tokenizer.detokenize([tid]))
            segments.append(seg)
    if return_segments:
        return texts, segments
    return texts
