"""Refcounted, hash-indexed prefix cache over the paged KV pool (ISSUE 6).

Concurrent requests that share a prompt prefix — the system-prompt
pattern of production serving — used to prefill and store the same K/V
pages once PER REQUEST. This module is the sharing layer the
continuous-batching engine (inference/engine.py) consults at admission:
prompt prefixes are indexed PAGE-ALIGNED (an entry per full page of
prompt tokens, keyed by the exact token prefix through that page), and
a cache hit maps the SAME physical pages into the new request's page
table instead of re-prefilling them. The ragged prefill kernel already
reads per-slot page tables (ops/prefill_attention.py), so sharing is
purely a scheduler/page-table change — no kernel work.

Sharing rules (each one is load-bearing for correctness):

- **Page-aligned, full pages only.** An entry covers tokens
  [0, depth*page_size) of some prompt, identified by its own page's
  exact token slice chained through its parent entry (dict-indexed,
  collision-free by construction — the dict keys ARE the tokens at
  every level; the full prefix is never materialized, since storing a
  full key tuple per depth would hold O(P^2) tokens for a P-page
  prefix). Partial trailing prompt pages are never registered: their
  pages also receive DECODE writes, so their content depends on the
  request that produced them, not just the prompt.
- **Cap at len(prompt) - 1.** At least one prompt token always
  prefills: the engine needs the forward's next-token logits for the
  LAST prompt position, and a fully-cached prompt has no forward to
  produce them.
- **Copy-on-write on the first divergent page.** When a prompt matches
  a cached prefix BEYOND its last full-page hit but diverges (or ends)
  mid-page, the matching leading rows of that page are still valid KV
  (position p's K/V depends only on tokens <= p, causal). The engine
  copies that page into a private page and resumes prefill at the
  divergence offset — the copy is the "write" the shared page must
  never see, since the new request's own suffix/decode K/V lands in
  exactly that page range.
- **Refcounts gate the free list.** A page referenced by any slot is
  never freed and never evicted. Release at refcount zero RETAINS
  registered pages in the cache (LRU-stamped, evictable); unregistered
  pages go back to the engine's free list.
- **LRU eviction, leaves first.** Under pool pressure the engine
  reclaims unreferenced cached pages longest-suffix-first (an entry
  with registered children is pinned by them — evicting a parent would
  orphan KV the children's positions depend on for matching). Every
  eviction batch logs loudly and counts toward `evicted_pages`.

Thread contract: every mutating call happens on the engine's serve
thread (admission/retirement are scheduler decisions); `stats()` reads
plain ints and is safe to sample from the metrics thread.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_logger = logging.getLogger(__name__)


@dataclass
class _Entry:
    """One cached full page of prompt prefix: `own` is the page's own
    token slice [(depth-1)*page_size, depth*page_size); the covered
    prefix is the `own` chain walked up through `parent_page` (pages
    are unique physical ids, so the parent page IS the parent's
    identity). `page` is the physical pool page holding the KV."""

    own: Tuple[int, ...]
    page: int
    parent_page: Optional[int]  # None for depth-1 entries
    depth: int  # pages of prompt prefix this entry completes
    last_used: int = 0  # LRU stamp, bumped on match and on release


@dataclass
class Match:
    """Admission-time lookup result. `pages` are the full-page hits in
    prefix order; `matched` counts ALL reusable tokens (full pages plus
    the valid leading rows of the COW page); `cow_src` is the physical
    page to copy when the match ends mid-page (None otherwise)."""

    pages: List[int] = field(default_factory=list)
    matched: int = 0
    cow_src: Optional[int] = None

    @property
    def full_pages(self) -> int:
        return len(self.pages)


class PrefixCache:
    def __init__(self, page_size: int):
        assert page_size >= 1
        self.page_size = page_size
        self._by_page: Dict[int, _Entry] = {}
        # trie edges: parent page (None = root) -> {own page tokens ->
        # child entry}. The per-page-tokens inner key lets lookup()
        # walk one page slice at a time (O(len(prompt)) total) instead
        # of rebuilding and hashing a fresh full-prefix tuple per depth
        # (O(L^2/ps) — the serve thread re-runs lookup every round for
        # a pool-blocked FIFO head, exactly when admission is already
        # under pressure), and keying nodes by their physical page
        # keeps stored tokens at O(prefix length) per chain instead of
        # O(P^2) full-key tuples. A parent with live children is never
        # evictable (their match walk depends on its tokens/KV).
        self._children: Dict[Optional[int],
                             Dict[Tuple[int, ...], _Entry]] = {}
        # slot references per page — ONLY pages the cache tracks
        # (entries); the engine free-lists everything else itself
        self._ref: Dict[int, int] = {}
        self._clock = 0  # LRU clock (monotonic, bumped per touch)

        # accounting (exported via DecodeEngine.counters)
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.hits = 0  # requests with matched > 0
        self.lookups = 0
        self.cow_copies = 0
        self.evicted_pages = 0
        self.inserted_pages = 0

    # -- lookup / acquire --------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def lookup(self, prompt: List[int]) -> Match:
        """Longest reusable prefix of `prompt`, capped at
        len(prompt) - 1 tokens: consecutive full-page entry hits from
        page 0, then at most one mid-page (COW) continuation among the
        last hit's children. Read-only — acquire() claims the result."""
        ps = self.page_size
        cap = len(prompt) - 1
        m = Match()
        depth = 0
        node_page: Optional[int] = None
        while (depth + 1) * ps <= cap:
            # one trie edge per page: hash only the page's own tokens,
            # never a rebuilt full-prefix tuple (O(len(prompt)) total)
            e = self._children.get(node_page, {}).get(
                tuple(prompt[depth * ps: (depth + 1) * ps]))
            if e is None:
                break
            depth += 1
            node_page = e.page
            m.pages.append(e.page)
            e.last_used = self._tick()
        m.matched = depth * ps
        # mid-page continuation: the child sharing the longest leading
        # run with the next prompt page is COW-shareable for that run
        nxt = prompt[depth * ps: (depth + 1) * ps]
        best, best_common = None, 0
        for own, e in self._children.get(node_page, {}).items():
            common = 0
            for a, b in zip(own, nxt):
                if a != b:
                    break
                common += 1
            if common > best_common:
                best, best_common = e, common
        valid = min(depth * ps + best_common, cap)
        if best is not None and valid > m.matched:
            m.cow_src = best.page
            m.matched = valid
            best.last_used = self._tick()
        return m

    def note(self, prompt_tokens: int, matched: int) -> None:
        """Book one ADMITTED request's hit accounting. Separate from
        lookup() on purpose: a pool-blocked FIFO head re-looks-up every
        scheduler round, and counting those retries would inflate the
        hit-rate gauge."""
        self.lookups += 1
        self.lookup_tokens += prompt_tokens
        if matched > 0:
            self.hits += 1
            self.hit_tokens += matched

    def acquire(self, match: Match) -> None:
        """Claim a lookup result for a slot: refcount every full-page
        hit AND the COW source (pinned against eviction until the page
        copy has been issued — release_page() drops that pin)."""
        for pg in match.pages:
            self._ref[pg] = self._ref.get(pg, 0) + 1
        if match.cow_src is not None:
            self._ref[match.cow_src] = self._ref.get(match.cow_src, 0) + 1

    def unacquire(self, match: Match) -> None:
        """Undo acquire() when admission backs out (pool still short
        after eviction): exact inverse, pages stay cached."""
        for pg in match.pages:
            self.release(pg)
        if match.cow_src is not None:
            self.release(match.cow_src)

    # -- registration / release --------------------------------------------

    def insert(self, prefix_tokens: List[int], page: int) -> bool:
        """Register `page` as the cache entry for the full-page prefix
        `prefix_tokens` (length must be a page multiple; the KV must
        already be written — the engine registers as prefill passes
        each boundary). The registering slot's reference carries over
        (refcount 1). Returns False when the key already exists (a
        concurrent request prefilled the same prefix first): the page
        stays untracked and the engine free-lists it at retirement."""
        assert len(prefix_tokens) % self.page_size == 0 and prefix_tokens
        ps = self.page_size
        depth = len(prefix_tokens) // ps
        parent_page: Optional[int] = None
        for d in range(depth - 1):
            pe = self._children.get(parent_page, {}).get(
                tuple(prefix_tokens[d * ps: (d + 1) * ps]))
            if pe is None:
                # broken parent chain (an ancestor evicted between this
                # slot's earlier boundary and now): the entry would be
                # unreachable by lookup's root walk — leave the page
                # untracked instead of caching garbage
                return False
            parent_page = pe.page
        own = tuple(prefix_tokens[(depth - 1) * ps:])
        kids = self._children.setdefault(parent_page, {})
        if own in kids:
            return False
        e = _Entry(own=own, page=page, parent_page=parent_page,
                   depth=depth, last_used=self._tick())
        kids[own] = e
        self._by_page[page] = e
        self._ref[page] = self._ref.get(page, 0) + 1
        self.inserted_pages += 1
        return True

    def insert_chain(self, prefix_tokens: List[int],
                     pages: List[int]) -> List[int]:
        """Register a TRANSFERRED page chain (cross-replica KV hand-off,
        ISSUE 17): page `pages[d-1]` holds the KV for prefix depth `d`.
        Unlike a prefilling slot's insert(), no slot references these
        pages — each successful insert's registering reference is
        dropped immediately, so the chain lands registered-but-
        unreferenced: the next lookup maps it for free, and eviction
        may reclaim it under pool pressure like any idle entry.
        Returns the pages the cache did NOT retain (prefix already
        cached here, or chain broken by concurrent eviction) — the
        caller free-lists those; their KV is bitwise identical to the
        retained entry's, so dropping duplicates loses nothing."""
        ps = self.page_size
        assert len(prefix_tokens) == len(pages) * ps and pages
        rejected: List[int] = []
        for d, pg in enumerate(pages, start=1):
            if self.insert(prefix_tokens[: d * ps], pg):
                retained = self.release(pg)
                assert retained  # fresh entry: registered, now idle
            else:
                rejected.append(pg)
        return rejected

    def owns(self, page: int) -> bool:
        return page in self._ref or page in self._by_page

    def release(self, page: int) -> bool:
        """Drop one slot reference. Returns True when the cache RETAINS
        the page (registered entry, or still referenced by another
        slot) — the caller must NOT free-list it; False hands the page
        back to the caller."""
        if page not in self._ref:
            return False  # never tracked: caller's page
        self._ref[page] -= 1
        if self._ref[page] > 0:
            return True
        del self._ref[page]
        e = self._by_page.get(page)
        if e is None:
            return False  # was only a COW-source pin on a foreign page
        e.last_used = self._tick()  # unreferenced now: LRU-evictable
        return True

    # alias with intent: dropping the temporary COW-source pin
    release_page = release

    # -- eviction ----------------------------------------------------------

    def _evictable(self) -> List[_Entry]:
        return [
            e for e in self._by_page.values()
            if self._ref.get(e.page, 0) == 0
            and not self._children.get(e.page)
        ]

    def evict(self, need_pages: int) -> List[int]:
        """Reclaim up to `need_pages` pages from unreferenced LEAF
        entries, least-recently-used first (evicting a leaf can expose
        its parent as the next candidate). Never touches a referenced
        page. One candidate scan + a heap per batch — this runs on the
        serve thread's admission path under pool pressure, exactly when
        a per-page rescan of every entry would hurt most. Loud: one
        warning per batch with the accounting."""
        import heapq

        freed: List[int] = []
        heap = [(e.last_used, e.page) for e in self._evictable()]
        heapq.heapify(heap)
        while heap and len(freed) < need_pages:
            _, page = heapq.heappop(heap)
            e = self._by_page.get(page)
            if (e is None or self._ref.get(page, 0)
                    or self._children.get(page)):
                continue  # stale heap entry
            del self._by_page[page]
            kids = self._children.get(e.parent_page)
            if kids is not None:
                kids.pop(e.own, None)
                if not kids:
                    del self._children[e.parent_page]
            self._children.pop(page, None)
            freed.append(page)
            pe = (self._by_page.get(e.parent_page)
                  if e.parent_page is not None else None)
            if (pe is not None and not self._children.get(pe.page)
                    and not self._ref.get(pe.page, 0)):
                heapq.heappush(heap, (pe.last_used, pe.page))
        if freed:
            self.evicted_pages += len(freed)
            _logger.warning(
                "prefix cache evicted %d page(s) under pool pressure "
                "(asked %d; %d entries / %d referenced pages remain; "
                "%d evicted lifetime) — raise page_budget if this is "
                "hot-path traffic",
                len(freed), need_pages, len(self._by_page),
                len(self._ref), self.evicted_pages,
            )
        return freed

    # -- accounting --------------------------------------------------------

    @property
    def cached_pages(self) -> int:
        return len(self._by_page)

    @property
    def shared_pages(self) -> int:
        """Pages currently mapped by MORE than one slot — the physical
        dedup the cache exists for. list() snapshots the dict in one
        C-level call: the serve thread mutates _ref without a lock, and
        a Python-level generator over live .values() could die with
        'dictionary changed size during iteration' under a concurrent
        /metrics poll."""
        return sum(1 for v in list(self._ref.values()) if v >= 2)

    @property
    def referenced_pages(self) -> int:
        return len(self._ref)

    def stats(self) -> dict:
        return {
            "prefix_hit_rate": round(
                self.hit_tokens / max(self.lookup_tokens, 1), 4),
            "prefix_hit_tokens": self.hit_tokens,
            "prefix_lookup_tokens": self.lookup_tokens,
            "prefix_hits": self.hits,
            "prefix_lookups": self.lookups,
            "prefix_cached_pages": self.cached_pages,
            "prefix_shared_pages": self.shared_pages,
            "prefix_cow_copies": self.cow_copies,
            "prefix_evicted_pages": self.evicted_pages,
        }
